"""Analytic cost + memory model over the parallelism strategy space.

Predicted step time and predicted peak per-device memory as PURE
FUNCTIONS of a :class:`Plan` (the point in the strategy lattice), a
:class:`ModelStats` (the workload) and a :class:`MeshSpec` (the
hardware) — the AMP/DistIR idea (arXiv:2210.07297, arXiv:2111.05426):
rank the lattice analytically, touch the accelerators only to run the
winner.

Step-time model (no-overlap, i.e. conservative: real runs overlap ring
hops with compute):

    t_step = t_compute · bubble(pipeline) + Σ t_collective

    t_compute    = 3 · F_fwd (+ remat refwd) · B_global / D / flops_dev
                   (backward ≈ 2× forward MACs)
    grad sync    = ring allreduce over 'data' (and 'seq'):
                   2·(n-1)/n · grad_bytes_local / bw(axis); ZeRO-1's
                   reduce-scatter + all-gather moves the SAME volume
                   (its win is memory + update FLOPs, not wire bytes)
    TP psums     = 4 per block (2 fwd + 2 bwd) of the [B_loc, S_loc, d]
                   residual stream over 'model'
    seq ring     = (sp-1) K/V neighbor hops per block (ring attention)
    pipeline     = bubble factor (M + pp - 1)/M on compute, plus the
                   microbatch boundary ppermute traffic over 'model'

Memory model (per device, bytes):

    params (f32) / TP·PP sharding
  + gradients (f32, same sharding; ×2 under grad accumulation — the
    scan carry holds the accumulator while a chunk's grads materialize)
  + optimizer state (slots × params; ÷ data-parallel ways under ZeRO-1)
  + BN running stats
  + activations of ONE microbatch (÷ seq ways; the TP-shardable
    portion ÷ model ways; remat keeps only block inputs + one live
    block's working set; a pipeline stage stashes every in-flight
    microbatch's boundary activation)
  + a fixed runtime overhead (compiled executables, collective
    scratch) — FIXED_OVERHEAD_BYTES, deliberately small so the model
    under-promises on tiny smoke configs rather than hiding headroom
    on real ones.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from dtf_tpu.plan.mesh_spec import MeshSpec, MiB
from dtf_tpu.plan.model_stats import ModelStats

# Optimizer-state slots per parameter (train/optimizer.py: keras_sgd
# keeps one velocity; adamw keeps mu+nu)
OPTIMIZER_SLOTS = {"sgd": 1, "momentum": 1, "adamw": 2}

# Fraction of HBM a plan may claim: XLA needs headroom for collective
# scratch and fusion temporaries beyond the model's own live set
HBM_FRACTION = 0.9

FIXED_OVERHEAD_BYTES = 64 * MiB


@dataclasses.dataclass(frozen=True)
class Plan:
    """One point in the strategy lattice.

    ``model`` (tensor-parallel ways) and ``pipeline`` (GPipe stages)
    both ride the runtime's 'model' mesh axis, so at most one of them
    may exceed 1; ``microbatch`` is sequential gradient-accumulation
    chunks for the dense families and the GPipe microbatch count for
    the pipeline family; ``zero`` is the ZeRO stage (this repo
    implements stage 1, --optimizer_sharding)."""

    data: int = 1
    model: int = 1
    seq: int = 1
    pipeline: int = 1
    zero: int = 0
    microbatch: int = 1
    remat: bool = False

    def __post_init__(self):
        for f in ("data", "model", "seq", "pipeline", "microbatch"):
            if getattr(self, f) < 1:
                raise ValueError(f"plan.{f} must be >= 1, got "
                                 f"{getattr(self, f)}")
        if self.zero not in (0, 1):
            raise ValueError(f"plan.zero must be 0 or 1 (this repo "
                             f"implements ZeRO-1), got {self.zero}")
        if self.model > 1 and self.pipeline > 1:
            raise ValueError(
                "plan.model and plan.pipeline both ride the 'model' mesh "
                "axis — at most one may exceed 1")

    @property
    def model_axis_size(self) -> int:
        """Size of the runtime's 'model' mesh axis (tensor ways or
        pipeline stages — one of the two is 1)."""
        return self.model * self.pipeline

    @property
    def num_devices(self) -> int:
        return self.data * self.seq * self.model_axis_size

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown plan fields {sorted(unknown)}; "
                             f"have {sorted(known)}")
        return cls(**d)

    def describe(self) -> str:
        parts = [f"dp={self.data}"]
        if self.model > 1:
            parts.append(f"tp={self.model}")
        if self.seq > 1:
            parts.append(f"sp={self.seq}")
        if self.pipeline > 1:
            parts.append(f"pp={self.pipeline}")
        if self.zero:
            parts.append(f"zero{self.zero}")
        if self.microbatch > 1:
            parts.append(f"micro={self.microbatch}")
        if self.remat:
            parts.append("remat")
        return "×".join(parts[:1]) + ("," + ",".join(parts[1:])
                                      if parts[1:] else "")


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """Prediction for one plan: seconds per step, peak per-device
    bytes, feasibility against the HBM budget, and the breakdown the
    CLI prints."""

    step_time_s: float
    peak_bytes: int
    hbm_budget_bytes: int
    compute_s: float
    comm_s: float
    breakdown: Dict[str, float]

    @property
    def feasible(self) -> bool:
        return self.peak_bytes <= self.hbm_budget_bytes

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["feasible"] = self.feasible
        return d


def check_plan(plan: Plan, stats: ModelStats, mesh: MeshSpec,
               global_batch: int) -> List[str]:
    """Hard-constraint violations of a plan for this workload/mesh —
    divisibility and capability rules mirroring what cli/runner.py and
    train/loop.py enforce at run construction.  Empty list = the plan
    compiles (memory feasibility is predict()'s separate verdict)."""
    v: List[str] = []
    if plan.num_devices != mesh.num_devices:
        v.append(f"plan uses {plan.num_devices} devices, mesh has "
                 f"{mesh.num_devices}")
    if plan.model > 1:
        if not stats.supports_tp:
            v.append(f"{stats.model}: tensor parallelism needs the plain "
                     f"transformer family")
        else:
            if stats.num_heads % plan.model:
                v.append(f"num_heads {stats.num_heads} % tp {plan.model}")
            if stats.d_ff % plan.model:
                v.append(f"d_ff {stats.d_ff} % tp {plan.model}")
    if plan.seq > 1:
        if not stats.supports_seq:
            v.append(f"{stats.model}: sequence parallelism needs the "
                     f"transformer family on token data")
        elif stats.seq_len % plan.seq:
            v.append(f"seq_len {stats.seq_len} % sp {plan.seq}")
    if plan.pipeline > 1:
        if not stats.supports_pipeline:
            v.append(f"{stats.model}: pipeline stages need the "
                     f"pipeline_transformer family")
        elif stats.num_layers % plan.pipeline:
            v.append(f"num_layers {stats.num_layers} % pp {plan.pipeline}")
    if plan.remat and not stats.supports_remat:
        v.append(f"{stats.model}: no remat policy for this family")
    if global_batch % plan.data:
        v.append(f"global batch {global_batch} % dp {plan.data}")
    else:
        per_replica = global_batch // plan.data
        if per_replica % plan.microbatch:
            v.append(f"per-replica batch {per_replica} % microbatch "
                     f"{plan.microbatch}")
    return v


# ---------------------------------------------------------------------------
# Prediction
# ---------------------------------------------------------------------------

def _axis_bw(mesh: MeshSpec, plan: Plan, axis: str) -> float:
    """Ring bandwidth for one mesh axis under the runtime's row-major
    ('data','seq','model') layout: 'model' is innermost (stride 1),
    'seq' strides over it, 'data' is outermost."""
    m = plan.model_axis_size
    stride, size = {
        "model": (1, m),
        "seq": (m, plan.seq),
        "data": (m * plan.seq, plan.data),
    }[axis]
    return mesh.axis_bandwidth(stride, size)


def _ring_s(bytes_: float, ways: int, bw: float) -> float:
    """Ring allreduce wall time: 2·(n-1)/n of the buffer crosses each
    device's link (reduce-scatter + all-gather halves)."""
    if ways <= 1 or bytes_ <= 0:
        return 0.0
    return 2.0 * (ways - 1) / ways * bytes_ / bw


def predict(plan: Plan, stats: ModelStats, mesh: MeshSpec,
            global_batch: int, optimizer: str = "sgd",
            hbm_fraction: float = HBM_FRACTION,
            device_flops: Optional[float] = None) -> PlanCost:
    """Predicted (step time, peak memory) for a valid plan.

    ``device_flops`` overrides the mesh's achievable-FLOP/s estimate —
    the calibration loop passes the measured probe here.  Call
    :func:`check_plan` first; predicting an invalid plan still returns
    numbers, they just describe a run the framework would refuse."""
    flops_dev = device_flops or mesh.device_flops
    slots = OPTIMIZER_SLOTS.get(optimizer)
    if slots is None:
        raise ValueError(f"unknown optimizer {optimizer!r}; have "
                         f"{sorted(OPTIMIZER_SLOTS)}")
    mp, pp, sp, dp = plan.model, plan.pipeline, plan.seq, plan.data
    micro_examples = max(global_batch // (dp * plan.microbatch), 1)

    # ---- parameters / gradients / optimizer state (f32) --------------
    param_local = 0.0
    fwd_flops = 0.0       # per example, whole model
    remat_refwd = 0.0     # extra forward FLOPs per example under remat
    act_local = 0.0       # per-device activation bytes, one microbatch
    max_block_act = 0.0   # live working set of the block being remat'd
    boundary_bytes = stats.seq_len * stats.d_model * stats.dtype_bytes \
        if stats.seq_len else 0
    for layer in stats.layers:
        p = float(layer.params)
        if layer.tp and mp > 1:
            p /= mp
        if layer.stage and pp > 1:
            p /= pp
        param_local += p
        fwd_flops += layer.flops
        la = float(layer.act_bytes)
        if mp > 1 and layer.act_tp_bytes:
            la -= layer.act_tp_bytes * (1.0 - 1.0 / mp)
        if plan.remat and layer.stage:
            remat_refwd += layer.flops
            la = float(layer.remat_act_bytes)
        if layer.stage and pp > 1:
            # this stage holds 1/pp of the stacked blocks...
            la /= pp
        la /= max(sp, 1)
        act_local += la
        if layer.stage:
            max_block_act = max(max_block_act,
                                float(layer.act_bytes) / max(sp, 1))
    param_bytes = param_local * 4
    grad_bytes = param_bytes * (2 if plan.microbatch > 1 else 1)
    opt_bytes = slots * param_bytes / (dp if plan.zero else 1)
    state_bytes = stats.state * 4

    act_bytes = act_local * micro_examples
    if plan.remat:
        # one block's full working set is live while it recomputes
        act_bytes += max_block_act * micro_examples
    if pp > 1:
        # GPipe stashes every in-flight microbatch's stage-boundary
        # activation for the backward pass
        act_bytes += (plan.microbatch * micro_examples
                      * boundary_bytes / max(sp, 1))

    peak = int(param_bytes + grad_bytes + opt_bytes + state_bytes
               + act_bytes + FIXED_OVERHEAD_BYTES)
    budget = int(mesh.hbm_bytes * hbm_fraction)

    # ---- compute ------------------------------------------------------
    # fwd + backward(≈2× MACs) + remat re-forward, ideal scaling over
    # every mesh axis (TP/SP/PP all divide the per-example work)
    flops_step = (3.0 * fwd_flops
                  + (remat_refwd if plan.remat else 0.0)) * global_batch
    compute_s = flops_step / plan.num_devices / flops_dev
    bubble = ((plan.microbatch + pp - 1) / plan.microbatch if pp > 1
              else 1.0)
    compute_s *= bubble

    # ---- collectives --------------------------------------------------
    breakdown: Dict[str, float] = {}
    t_grad = _ring_s(param_local * 4, dp, _axis_bw(mesh, plan, "data"))
    t_grad += _ring_s(param_local * 4, sp, _axis_bw(mesh, plan, "seq"))
    breakdown["grad_sync_s"] = t_grad

    t_tp = 0.0
    if mp > 1:
        stream = (global_batch // dp) * (stats.seq_len / max(sp, 1)) \
            * stats.d_model * stats.dtype_bytes
        n_blocks = sum(1 for l in stats.layers if l.stage)
        t_tp = _ring_s(4.0 * n_blocks * stream, mp,
                       _axis_bw(mesh, plan, "model"))
    breakdown["tp_psum_s"] = t_tp

    t_ring = 0.0
    if sp > 1:
        # ring attention: (sp-1) neighbor hops of the local K+V per
        # block, forward and backward
        n_blocks = sum(1 for l in stats.layers if l.stage)
        kv_local = 2.0 * (stats.seq_len / sp) * stats.d_model \
            * stats.dtype_bytes * (global_batch // dp)
        t_ring = (2.0 * n_blocks * (sp - 1) * kv_local
                  / _axis_bw(mesh, plan, "seq"))
    breakdown["seq_ring_s"] = t_ring

    t_pipe = 0.0
    if pp > 1:
        t_pipe = (2.0 * plan.microbatch * micro_examples * boundary_bytes
                  / _axis_bw(mesh, plan, "model"))
    breakdown["pipeline_xfer_s"] = t_pipe

    comm_s = t_grad + t_tp + t_ring + t_pipe
    breakdown.update(
        compute_s=compute_s, bubble_factor=bubble,
        param_bytes=param_bytes, grad_bytes=grad_bytes,
        opt_bytes=opt_bytes, act_bytes=act_bytes)
    return PlanCost(step_time_s=compute_s + comm_s, peak_bytes=peak,
                    hbm_budget_bytes=budget, compute_s=compute_s,
                    comm_s=comm_s, breakdown=breakdown)
