"""Analytic cost + memory model over the parallelism strategy space.

Predicted step time and predicted peak per-device memory as PURE
FUNCTIONS of a :class:`Plan` (the point in the strategy lattice), a
:class:`ModelStats` (the workload) and a :class:`MeshSpec` (the
hardware) — the AMP/DistIR idea (arXiv:2210.07297, arXiv:2111.05426):
rank the lattice analytically, touch the accelerators only to run the
winner.

Step-time model:

    t_step = t_compute · bubble(pipeline) + exposed(grad sync)
             + t_tp_psum + t_seq_ring + t_pipe_xfer

    t_compute    = 3 · F_fwd (+ remat refwd) · B_global / D / flops_dev
                   (backward ≈ 2× forward MACs)
    grad sync    = data-axis (and 'seq') weight collectives, staged:
                     zero ∈ {0,1}: one ring allreduce — 2·(n-1)/n ·
                       grad_bytes_local / bw; ZeRO-1's reduce-scatter
                       + all-gather moves the SAME volume (its win is
                       memory + update FLOPs, not wire bytes)
                     zero ∈ {2,3}: (m + 1) half-collectives — one
                       reduce-scatter per microbatch (grads shard as
                       the backward produces them) + one param
                       all-gather (stage 2: post-update; stage 3:
                       pre-compute), each (n-1)/n · bytes / bw.  At
                       m=1 the volume equals the allreduce.
    overlap      = stages 2/3 schedule their collectives per leaf /
                   per microbatch, so XLA hides part of them behind
                   fwd/bwd compute: hidden = min(t_grad,
                   overlap_frac · t_compute), exposed = t_grad −
                   hidden.  ``overlap_frac`` defaults to
                   DEFAULT_OVERLAP_FRAC and is calibrated against the
                   measured --zero_probe gauges (plan_main
                   --calibrate emits the run's implied fraction).
                   Stages 0/1 do ONE monolithic end-of-step sync in
                   this repo — no overlap credit.
    TP psums     = 4 per block (2 fwd + 2 bwd) of the [B_loc, S_loc, d]
                   residual stream over 'model'
    seq ring     = (sp-1) K/V neighbor hops per block (ring attention)
    pipeline     = bubble factor (M + pp - 1)/M on compute, plus the
                   microbatch boundary ppermute traffic over 'model'

Memory model (per device, bytes):

    params (f32) / TP·PP sharding; ZeRO-3 holds the persistent copy
    sliced (÷ dp) but still materializes the gathered working copy
    during fwd/bwd — counted in full, honestly: the saving over the
    replicated stages is the OTHER buffers
  + gradients (f32, same sharding): zero < 2 pays the full buffer (×2
    under grad accumulation — the scan carry holds the accumulator
    while a chunk's grads materialize); zero ∈ {2,3} holds a 1/dp
    sliced accumulator plus one layer's transient full grad (each
    leaf's psum_scatter consumes it as the backward produces it)
  + optimizer state (slots × params; ÷ dp under any ZeRO stage)
  + BN running stats
  + activations of ONE microbatch (÷ seq ways; the TP-shardable
    portion ÷ model ways; remat keeps only block inputs + one live
    block's working set; a pipeline stage stashes every in-flight
    microbatch's boundary activation)
  + a fixed runtime overhead (compiled executables, collective
    scratch) — FIXED_OVERHEAD_BYTES, deliberately small so the model
    under-promises on tiny smoke configs rather than hiding headroom
    on real ones.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from dtf_tpu.plan.mesh_spec import MeshSpec, MiB
from dtf_tpu.plan.model_stats import ModelStats

# Optimizer-state slots per parameter (train/optimizer.py: keras_sgd
# keeps one velocity; adamw keeps mu+nu)
OPTIMIZER_SLOTS = {"sgd": 1, "momentum": 1, "adamw": 2}

# Fraction of HBM a plan may claim: XLA needs headroom for collective
# scratch and fusion temporaries beyond the model's own live set
HBM_FRACTION = 0.9

FIXED_OVERHEAD_BYTES = 64 * MiB

# Fraction of compute the ZeRO-2/3 per-leaf collectives can hide
# behind (XLA latency-hiding scheduler).  Deliberately conservative;
# calibrate against the measured --zero_probe exposed-comm gauges and
# override via predict(..., overlap_frac=) / plan_main --overlap_frac.
DEFAULT_OVERLAP_FRAC = 0.5


@dataclasses.dataclass(frozen=True)
class Plan:
    """One point in the strategy lattice.

    ``model`` (tensor-parallel ways) and ``pipeline`` (GPipe stages)
    both ride the runtime's 'model' mesh axis, so at most one of them
    may exceed 1; ``microbatch`` is sequential gradient-accumulation
    chunks for the dense families and the GPipe microbatch count for
    the pipeline family; ``zero`` is the ZeRO stage 0-3 (1 = sharded
    optimizer state, 2 = + sharded gradients, 3 = + sharded params —
    train/zero.py)."""

    data: int = 1
    model: int = 1
    seq: int = 1
    pipeline: int = 1
    zero: int = 0
    microbatch: int = 1
    remat: bool = False

    def __post_init__(self):
        for f in ("data", "model", "seq", "pipeline", "microbatch"):
            if getattr(self, f) < 1:
                raise ValueError(f"plan.{f} must be >= 1, got "
                                 f"{getattr(self, f)}")
        if self.zero not in (0, 1, 2, 3):
            raise ValueError(f"plan.zero must be a ZeRO stage in "
                             f"0..3, got {self.zero}")
        if self.model > 1 and self.pipeline > 1:
            raise ValueError(
                "plan.model and plan.pipeline both ride the 'model' mesh "
                "axis — at most one may exceed 1")

    @property
    def model_axis_size(self) -> int:
        """Size of the runtime's 'model' mesh axis (tensor ways or
        pipeline stages — one of the two is 1)."""
        return self.model * self.pipeline

    @property
    def num_devices(self) -> int:
        return self.data * self.seq * self.model_axis_size

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown plan fields {sorted(unknown)}; "
                             f"have {sorted(known)}")
        return cls(**d)

    def describe(self) -> str:
        parts = [f"dp={self.data}"]
        if self.model > 1:
            parts.append(f"tp={self.model}")
        if self.seq > 1:
            parts.append(f"sp={self.seq}")
        if self.pipeline > 1:
            parts.append(f"pp={self.pipeline}")
        if self.zero:
            parts.append(f"zero{self.zero}")
        if self.microbatch > 1:
            parts.append(f"micro={self.microbatch}")
        if self.remat:
            parts.append("remat")
        return "×".join(parts[:1]) + ("," + ",".join(parts[1:])
                                      if parts[1:] else "")


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """Prediction for one plan: seconds per step, peak per-device
    bytes, feasibility against the HBM budget, and the breakdown the
    CLI prints."""

    step_time_s: float
    peak_bytes: int
    hbm_budget_bytes: int
    compute_s: float
    comm_s: float
    breakdown: Dict[str, float]

    @property
    def feasible(self) -> bool:
        return self.peak_bytes <= self.hbm_budget_bytes

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["feasible"] = self.feasible
        return d


def check_plan(plan: Plan, stats: ModelStats, mesh: MeshSpec,
               global_batch: int) -> List[str]:
    """Hard-constraint violations of a plan for this workload/mesh —
    divisibility and capability rules mirroring what cli/runner.py and
    train/loop.py enforce at run construction.  Empty list = the plan
    compiles (memory feasibility is predict()'s separate verdict)."""
    v: List[str] = []
    if plan.num_devices != mesh.num_devices:
        v.append(f"plan uses {plan.num_devices} devices, mesh has "
                 f"{mesh.num_devices}")
    if plan.model > 1:
        if not stats.supports_tp:
            v.append(f"{stats.model}: tensor parallelism needs the plain "
                     f"transformer family")
        else:
            if stats.num_heads % plan.model:
                v.append(f"num_heads {stats.num_heads} % tp {plan.model}")
            if stats.d_ff % plan.model:
                v.append(f"d_ff {stats.d_ff} % tp {plan.model}")
    if plan.seq > 1:
        if not stats.supports_seq:
            v.append(f"{stats.model}: sequence parallelism needs the "
                     f"transformer family on token data")
        elif stats.seq_len % plan.seq:
            v.append(f"seq_len {stats.seq_len} % sp {plan.seq}")
    if plan.pipeline > 1:
        if not stats.supports_pipeline:
            v.append(f"{stats.model}: pipeline stages need the "
                     f"pipeline_transformer family")
        elif stats.num_layers % plan.pipeline:
            v.append(f"num_layers {stats.num_layers} % pp {plan.pipeline}")
    if plan.remat and not stats.supports_remat:
        v.append(f"{stats.model}: no remat policy for this family")
    if global_batch % plan.data:
        v.append(f"global batch {global_batch} % dp {plan.data}")
    else:
        per_replica = global_batch // plan.data
        if per_replica % plan.microbatch:
            v.append(f"per-replica batch {per_replica} % microbatch "
                     f"{plan.microbatch}")
    return v


# ---------------------------------------------------------------------------
# Prediction
# ---------------------------------------------------------------------------

def _axis_bw(mesh: MeshSpec, plan: Plan, axis: str) -> float:
    """Ring bandwidth for one mesh axis under the runtime's row-major
    ('data','seq','model') layout: 'model' is innermost (stride 1),
    'seq' strides over it, 'data' is outermost."""
    m = plan.model_axis_size
    stride, size = {
        "model": (1, m),
        "seq": (m, plan.seq),
        "data": (m * plan.seq, plan.data),
    }[axis]
    return mesh.axis_bandwidth(stride, size)


def _ring_s(bytes_: float, ways: int, bw: float) -> float:
    """Ring allreduce wall time: 2·(n-1)/n of the buffer crosses each
    device's link (reduce-scatter + all-gather halves)."""
    if ways <= 1 or bytes_ <= 0:
        return 0.0
    return 2.0 * (ways - 1) / ways * bytes_ / bw


def predict(plan: Plan, stats: ModelStats, mesh: MeshSpec,
            global_batch: int, optimizer: str = "sgd",
            hbm_fraction: float = HBM_FRACTION,
            device_flops: Optional[float] = None,
            overlap_frac: float = DEFAULT_OVERLAP_FRAC) -> PlanCost:
    """Predicted (step time, peak memory) for a valid plan.

    ``device_flops`` overrides the mesh's achievable-FLOP/s estimate —
    the calibration loop passes the measured probe here.
    ``overlap_frac`` is the fraction of compute the ZeRO-2/3 per-leaf
    collectives may hide behind (stages 0/1 sync monolithically and
    get no credit).  Call :func:`check_plan` first; predicting an
    invalid plan still returns numbers, they just describe a run the
    framework would refuse."""
    if not 0.0 <= overlap_frac <= 1.0:
        raise ValueError(f"overlap_frac must be in [0, 1], got "
                         f"{overlap_frac}")
    flops_dev = device_flops or mesh.device_flops
    slots = OPTIMIZER_SLOTS.get(optimizer)
    if slots is None:
        raise ValueError(f"unknown optimizer {optimizer!r}; have "
                         f"{sorted(OPTIMIZER_SLOTS)}")
    mp, pp, sp, dp = plan.model, plan.pipeline, plan.seq, plan.data
    micro_examples = max(global_batch // (dp * plan.microbatch), 1)

    # ---- parameters / gradients / optimizer state (f32) --------------
    param_local = 0.0
    max_layer_param = 0.0  # largest single layer's local params
    fwd_flops = 0.0       # per example, whole model
    remat_refwd = 0.0     # extra forward FLOPs per example under remat
    act_local = 0.0       # per-device activation bytes, one microbatch
    max_block_act = 0.0   # live working set of the block being remat'd
    boundary_bytes = stats.seq_len * stats.d_model * stats.dtype_bytes \
        if stats.seq_len else 0
    for layer in stats.layers:
        p = float(layer.params)
        if layer.tp and mp > 1:
            p /= mp
        if layer.stage and pp > 1:
            p /= pp
        param_local += p
        max_layer_param = max(max_layer_param, p)
        fwd_flops += layer.flops
        la = float(layer.act_bytes)
        if mp > 1 and layer.act_tp_bytes:
            la -= layer.act_tp_bytes * (1.0 - 1.0 / mp)
        if plan.remat and layer.stage:
            remat_refwd += layer.flops
            la = float(layer.remat_act_bytes)
        if layer.stage and pp > 1:
            # this stage holds 1/pp of the stacked blocks...
            la /= pp
        la /= max(sp, 1)
        act_local += la
        if layer.stage:
            max_block_act = max(max_block_act,
                                float(layer.act_bytes) / max(sp, 1))
    param_bytes = param_local * 4
    # stage >= 2's sharded gradient ACCUMULATOR only exists when the
    # outer grad-accumulation scan runs (microbatch > 1, dense
    # families — the pipeline family's GPipe scan accumulates a full
    # grad tree internally): sliced 1/dp carry + one layer's transient
    # full grad (each leaf's reduce-scatter consumes it as the
    # backward emits it).  At microbatch=1 the emitted program
    # materializes the full grad tree exactly like stages 0/1 do, so
    # it is priced identically — two identical programs must not get
    # different predictions.
    if plan.zero >= 2 and plan.microbatch > 1 and pp == 1:
        grad_bytes = param_bytes / dp + max_layer_param * 4
    else:
        grad_bytes = param_bytes * (2 if plan.microbatch > 1 else 1)
    opt_bytes = slots * param_bytes / (dp if plan.zero else 1)
    if plan.zero == 3:
        # persistent copy sliced; the gathered working copy still
        # counted IN FULL — the per-leaf gathers live through the
        # backward in the emitted program (honest accounting: ZeRO-3's
        # win here is the grads + optimizer terms, and the sliced
        # persistent set between steps)
        param_term = param_bytes / dp + param_bytes
    else:
        param_term = param_bytes
    state_bytes = stats.state * 4

    act_bytes = act_local * micro_examples
    if plan.remat:
        # one block's full working set is live while it recomputes
        act_bytes += max_block_act * micro_examples
    if pp > 1:
        # GPipe stashes every in-flight microbatch's stage-boundary
        # activation for the backward pass
        act_bytes += (plan.microbatch * micro_examples
                      * boundary_bytes / max(sp, 1))

    peak = int(param_term + grad_bytes + opt_bytes + state_bytes
               + act_bytes + FIXED_OVERHEAD_BYTES)
    budget = int(mesh.hbm_bytes * hbm_fraction)

    # ---- compute ------------------------------------------------------
    # fwd + backward(≈2× MACs) + remat re-forward, ideal scaling over
    # every mesh axis (TP/SP/PP all divide the per-example work)
    flops_step = (3.0 * fwd_flops
                  + (remat_refwd if plan.remat else 0.0)) * global_batch
    compute_s = flops_step / plan.num_devices / flops_dev
    bubble = ((plan.microbatch + pp - 1) / plan.microbatch if pp > 1
              else 1.0)
    compute_s *= bubble

    # ---- collectives --------------------------------------------------
    breakdown: Dict[str, float] = {}
    if plan.zero >= 2:
        # one reduce-scatter per microbatch (the pipeline family's
        # grads arrive once from the GPipe scan, so it scatters once)
        # + one param all-gather, each a HALF allreduce; at m=1 the
        # volume equals the stage-0/1 ring allreduce
        scatters = plan.microbatch if pp == 1 else 1
        halves = (scatters + 1) / 2.0
        t_grad = halves * _ring_s(param_local * 4, dp,
                                  _axis_bw(mesh, plan, "data"))
        t_grad += halves * _ring_s(param_local * 4, sp,
                                   _axis_bw(mesh, plan, "seq"))
    else:
        t_grad = _ring_s(param_local * 4, dp, _axis_bw(mesh, plan, "data"))
        t_grad += _ring_s(param_local * 4, sp, _axis_bw(mesh, plan, "seq"))
    breakdown["grad_sync_s"] = t_grad
    # compute/comm overlap: credit only the collectives whose SCHEDULE
    # differs from the monolithic end-of-step sync.  Of stage >= 2's
    # (scatters + 1) half-collectives, the per-microbatch scatters
    # EXCEPT THE LAST interleave with the following chunk's compute,
    # and stage 3's pre-compute param gather interleaves with the
    # forward; the final scatter (and stage 2's post-update gather)
    # stay exposed.  At m=1 stage 2 therefore earns NO credit — it
    # emits the same program as stage 1 and must be priced like it.
    hidden = 0.0
    ov_share = 0.0
    if plan.zero >= 2:
        scatters = plan.microbatch if pp == 1 else 1
        ov_halves = (scatters - 1) + (1 if plan.zero == 3 else 0)
        ov_share = ov_halves / (scatters + 1)
    if ov_share > 0 and overlap_frac > 0:
        hidden = min(ov_share * t_grad, overlap_frac * compute_s)
    t_grad_exposed = t_grad - hidden
    breakdown["hidden_comm_s"] = hidden
    breakdown["overlap_frac"] = overlap_frac if ov_share > 0 else 0.0

    t_tp = 0.0
    if mp > 1:
        stream = (global_batch // dp) * (stats.seq_len / max(sp, 1)) \
            * stats.d_model * stats.dtype_bytes
        n_blocks = sum(1 for l in stats.layers if l.stage)
        t_tp = _ring_s(4.0 * n_blocks * stream, mp,
                       _axis_bw(mesh, plan, "model"))
    breakdown["tp_psum_s"] = t_tp

    t_ring = 0.0
    if sp > 1:
        # ring attention: (sp-1) neighbor hops of the local K+V per
        # block, forward and backward
        n_blocks = sum(1 for l in stats.layers if l.stage)
        kv_local = 2.0 * (stats.seq_len / sp) * stats.d_model \
            * stats.dtype_bytes * (global_batch // dp)
        t_ring = (2.0 * n_blocks * (sp - 1) * kv_local
                  / _axis_bw(mesh, plan, "seq"))
    breakdown["seq_ring_s"] = t_ring

    t_pipe = 0.0
    if pp > 1:
        t_pipe = (2.0 * plan.microbatch * micro_examples * boundary_bytes
                  / _axis_bw(mesh, plan, "model"))
    breakdown["pipeline_xfer_s"] = t_pipe

    comm_s = t_grad_exposed + t_tp + t_ring + t_pipe
    breakdown.update(
        compute_s=compute_s, bubble_factor=bubble,
        exposed_comm_s=comm_s,
        param_bytes=param_bytes, param_term_bytes=param_term,
        grad_bytes=grad_bytes,
        opt_bytes=opt_bytes, act_bytes=act_bytes)
    return PlanCost(step_time_s=compute_s + comm_s, peak_bytes=peak,
                    hbm_budget_bytes=budget, compute_s=compute_s,
                    comm_s=comm_s, breakdown=breakdown)
