"""Parallelism planner — analytic cost/memory model over the strategy
space, feasible-plan search, and plan→config compilation.

The repo exposes every parallelism lever the cluster experiments
motivate — 'model'/'seq' mesh axes, ZeRO-1 optimizer sharding, pipeline
stages, gradient accumulation, remat — but until this package choosing
among them was operator folklore: flags were hand-tuned per run and a
bad combination only revealed itself as an OOM or a 2× step-time
regression on real hardware.  Following AMP (arXiv:2210.07297) and
DistIR (arXiv:2111.05426), an analytic cost+memory model over the plan
lattice picks near-optimal plans without touching the accelerators:
plans for a 4-host × 4-device pod are computed on a CPU box in
milliseconds.

Layers:
  mesh_spec   — MeshSpec: devices, HBM, achievable FLOP/s, intra/inter
                host bandwidth (presets + "k=v,…" parser + a live-probe
                FLOP/s calibration)
  model_stats — per-layer param counts, forward FLOPs and activation
                bytes derived from the registry's model configs
                (transformer + resnet families)
  cost_model  — Plan dataclass (data/model/seq × zero × pipeline ×
                microbatch × remat) → predicted step time + peak HBM,
                both pure functions
  search      — enumerate the feasible lattice under the HBM budget,
                rank by predicted step time, emit a ranked JSON artifact
  compile     — Plan ↔ the existing config flags (`--plan auto|<file>`);
                a plan-selected run is bit-identical to the same flags
                set by hand (test-asserted)
  serve_trace — serving WORKLOADS: per-request reconstruction from
                recorded router/replica traces, synthetic Poisson/
                burst/shared-prefix arrival generators
  serve_model — the serving-capacity simulator: replay a workload
                through an analytic fleet model (TP × replicas × page
                pool × chunking × deadlines) and answer what-ifs —
                replicas for X req/s at a p99 SLO, TP-vs-replicas at
                fixed chips, pool size vs shed rate — calibrated
                against measured runs like the training planner

CLIs: ``python -m dtf_tpu.cli.plan_main`` (rank / --check /
--calibrate) and ``python -m dtf_tpu.cli.plan_serve_main`` (serving
what-ifs / --calibrate).
"""

from dtf_tpu.plan.cost_model import Plan, PlanCost, predict, check_plan
from dtf_tpu.plan.mesh_spec import MeshSpec, mesh_spec
from dtf_tpu.plan.model_stats import ModelStats, characterize
from dtf_tpu.plan.search import search, ranked_artifact
from dtf_tpu.plan.compile import (apply_plan, load_plan_file,
                                  plan_from_config, resolve_plan)
from dtf_tpu.plan.serve_trace import (RequestRecord, Workload,
                                      measured_stats, parse_workload,
                                      scale_workload,
                                      synthetic_workload,
                                      workload_from_records)
from dtf_tpu.plan.serve_model import (FleetConfig, FleetPrediction,
                                      ServeProfile, pool_vs_shed,
                                      rank_tp_vs_replicas,
                                      replicas_for, simulate)

__all__ = [
    "Plan", "PlanCost", "predict", "check_plan",
    "MeshSpec", "mesh_spec",
    "ModelStats", "characterize",
    "search", "ranked_artifact",
    "apply_plan", "load_plan_file", "plan_from_config", "resolve_plan",
    "RequestRecord", "Workload", "measured_stats", "parse_workload",
    "scale_workload", "synthetic_workload", "workload_from_records",
    "FleetConfig", "FleetPrediction", "ServeProfile", "pool_vs_shed",
    "rank_tp_vs_replicas", "replicas_for", "simulate",
]
