"""Trace-driven serving-capacity simulator — the analytic fleet model.

The training planner (plan/cost_model.py) ranks parallelism plans by
predicting step time from an analytic model calibrated against
measurement.  This module extends that discipline from training steps
to serving FLEETS: replay an arrival process (plan/serve_trace.py —
recorded router/replica traces, or synthetic Poisson/burst/shared-
prefix mixes) through an analytic model of the serving tier and answer
capacity questions without burning hardware — the DistIR/AMP idea
(arXiv:2111.05426, arXiv:2210.07297) pointed at the replica tier.

The model is the SERVING STACK'S OWN ARCHITECTURE, miniaturized:

  router    — admission bound (shed past ``admission_limit``
              outstanding), placement (prefix-affine with least-loaded
              fallback, or pure least-loaded), per-replica inflight
              cap, Backpressure when every replica's queue is full,
              deadline verdicts.
  replica   — the engine loop, iteration-granular: each iteration runs
              at most ONE prefill chunk (round-robin across prefilling
              slots — the PR-3 scheduling contract) plus one decode
              step advancing every decoding slot by one token;
              iteration wall time is the calibrated chunk/step service
              times (plus a per-iteration overhead term).
  admission — the engine's page math: a request needs
              ⌈(prompt + budget) / page_size⌉ pages, FIFO head-of-line
              when the pool cannot cover them, registry-only prefix
              pages evicted to un-starve admission.
  prefix    — a registry model per replica: the first completed
              prefill of a group registers its full prompt pages;
              later admits of the group share them (fewer fresh pages,
              fewer prefill chunks).  Parsed traces carry measured
              share depth instead of group identity — those hits are
              replayed as recorded.

Service times come from the MFU ledger / trace spans of a real run
(``ServeProfile.from_records``): decode-step and prefill-chunk wall
times are MEDIANS of the recorded spans (medians because the stream
includes compile outliers), flops ride along for documentation.
Tensor parallelism is modeled as an Amdahl split of the measured step:
``t(tp) = t(tp_base) · (tp_comm_frac + (1 − tp_comm_frac) ·
tp_base/tp)`` — compute shards, a documented fraction (psums + host
dispatch) does not.  A TP replica's page pool scales WITH tp by
default (the KV pool is head-sharded, so k chips hold k× the pages at
equal per-chip HBM) — that coupling is exactly why TP-vs-replicas at
fixed chips is a real trade and not arithmetic.

Deadlines are post-hoc verdicts: a request whose simulated completion
exceeds its deadline counts as a deadline failure (its tokens don't
count toward throughput).  The real router frees capacity at the
deadline instead of at completion, so the simulator is conservative.
Service times are jittered from the MEASURED per-step spread when the
profile carries one (``ServeProfile.jitter`` — the recorded
``serve_decode`` span durations normalized by their median,
resampled by a seeded in-module PRNG so predictions stay
deterministic); with jitter on, ``hedge_s`` is a real policy: a
request stuck in a straggling replica's queue past the hedge bar is
re-dispatched to a strictly less-loaded sibling, the simulator's
model of the router's duplicate-dispatch race.  Without jitter
nothing straggles and the knob stays a recorded no-op.

Disaggregation is a what-if (:func:`pool_split`): at a fixed chip
budget, compare the colocated tier against every prefill:decode
replica split.  The decode pool's "prefill" is KV-page MIGRATION —
each chunk-equivalent of prompt pages crosses the fabric at a
documented wire bandwidth plus a per-window latency
(``serve/migrate.py``'s windowed ``page_fetch`` protocol,
miniaturized) — so the trade the model captures is real: a split
buys the decode pool freedom from prefill head-of-line blocking and
pays for it in wire time and a thinner decode fleet.

Calibration contract (the PR-5 ``--calibrate`` shape): predicted
tokens/s and p99 latency must land within a documented ratio bar
(default 2×) of a measured traced run — ``plan_serve_main
--calibrate`` records the run, replays it, exports
``plan_serve_tokens_ratio`` / ``plan_serve_p99_ratio`` gauges to the
obs registry, and exits nonzero outside the bar.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from dtf_tpu.obs.registry import percentile
from dtf_tpu.plan.serve_trace import Workload

#: default feasibility bar for the what-if answers: a config "serves"
#: a workload when sheds + deadline failures stay under this fraction
DEFAULT_LOSS_BAR = 0.01

#: jitter extraction: need at least this many decode spans for the
#: spread to mean anything, keep about this many (evenly strided
#: over the SORTED durations, so the tails survive the cap)
_JITTER_MIN_SPANS = 8
_JITTER_SAMPLES = 256


@dataclasses.dataclass(frozen=True)
class ServeProfile:
    """Calibrated per-engine service times (one replica at ``tp_base``).

    ``decode_step_s`` is one full-batch decode step (weight-bound: the
    step reads all params regardless of how many slots decode, which
    is why the simulator charges it per ITERATION, not per token);
    ``prefill_chunk_s`` is one ``chunk_tokens``-token prefill chunk.
    ``overhead_s`` is per engine iteration (host-side scheduling not
    inside either span).  ``jitter`` is the measured per-step spread:
    each recorded decode span's duration divided by the stream's
    median, sorted — the simulator resamples it per iteration so
    stragglers happen at their MEASURED frequency, not a modeled
    one.  Empty = deterministic service times (the pre-calibration
    default)."""

    decode_step_s: float
    prefill_chunk_s: float
    chunk_tokens: int = 64
    page_size: int = 16
    overhead_s: float = 0.0
    decode_flops: float = 0.0
    tp_base: int = 1
    tp_comm_frac: float = 0.15
    jitter: Tuple[float, ...] = ()

    def __post_init__(self):
        if self.decode_step_s <= 0 or self.prefill_chunk_s <= 0:
            raise ValueError("decode_step_s and prefill_chunk_s must be "
                             "positive (a zero service time simulates "
                             "an infinitely fast fleet)")
        if self.chunk_tokens < 1 or self.page_size < 1:
            raise ValueError("chunk_tokens and page_size must be >= 1")
        if not 0.0 <= self.tp_comm_frac < 1.0:
            raise ValueError(f"tp_comm_frac must be in [0, 1), got "
                             f"{self.tp_comm_frac}")
        # lists parse out of JSON artifacts; store the canonical tuple
        object.__setattr__(self, "jitter", tuple(self.jitter))
        if any(j <= 0 for j in self.jitter):
            raise ValueError("jitter factors must be positive "
                             "(dur / median of measured spans)")

    def decode_step_for(self, tp: int) -> float:
        """Amdahl model of TP scaling around the measured base: the
        compute fraction shards over ``tp``, ``tp_comm_frac`` (psums,
        host dispatch) does not."""
        if tp == self.tp_base:
            return self.decode_step_s
        return self.decode_step_s * (
            self.tp_comm_frac
            + (1.0 - self.tp_comm_frac) * self.tp_base / tp)

    def prefill_chunk_for(self, tp: int) -> float:
        return self.prefill_chunk_s * (
            self.tp_comm_frac
            + (1.0 - self.tp_comm_frac) * self.tp_base / tp) \
            if tp != self.tp_base else self.prefill_chunk_s

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_records(cls, merged: List[dict], **overrides
                     ) -> "ServeProfile":
        """Profile from a traced serving run's own records: MEDIAN
        ``serve_decode`` / ``serve_prefill_chunk`` span wall times
        (median, not mean — the stream includes the compile-step
        outliers the ledger drops), modal chunk size from the chunk
        spans, per-step flops from the ledger, and the decode spans'
        normalized spread as the ``jitter`` distribution (capped at
        ``_JITTER_SAMPLES`` evenly-strided samples so a long trace
        doesn't bloat the profile).  ``overrides`` win over extracted
        values (and supply anything the trace lacks)."""
        decode_durs: List[float] = []
        chunk_durs: List[float] = []
        chunk_sizes: List[int] = []
        flops = 0.0
        for rec in merged:
            if rec.get("kind") == "span":
                if rec.get("name") == "serve_decode":
                    decode_durs.append(float(rec.get("dur_s", 0.0)))
                elif rec.get("name") == "serve_prefill_chunk":
                    chunk_durs.append(float(rec.get("dur_s", 0.0)))
                    if rec.get("tokens"):
                        chunk_sizes.append(int(rec["tokens"]))
            elif (rec.get("name") == "ledger_exec"
                  and rec.get("exec") == "serve_decode_step"):
                flops = float(rec.get("flops", 0.0) or 0.0)
        values: Dict[str, object] = {}
        if decode_durs:
            med = percentile(sorted(decode_durs), 50.0)
            values["decode_step_s"] = med
            if med > 0 and len(decode_durs) >= _JITTER_MIN_SPANS:
                facs = sorted(round(d / med, 6) for d in decode_durs
                              if d > 0)
                stride = max(1, len(facs) // _JITTER_SAMPLES)
                values["jitter"] = tuple(facs[::stride])
        if chunk_durs:
            values["prefill_chunk_s"] = percentile(sorted(chunk_durs),
                                                   50.0)
        if chunk_sizes:
            values["chunk_tokens"] = max(set(chunk_sizes),
                                         key=chunk_sizes.count)
        if flops:
            values["decode_flops"] = flops
        values.update(overrides)
        missing = {"decode_step_s", "prefill_chunk_s"} - set(values)
        if missing:
            raise ValueError(
                f"trace carries no {sorted(missing)} measurement "
                f"(serve_decode / serve_prefill_chunk spans) — pass "
                f"explicit values, or record a traced serving run")
        return cls(**values)  # type: ignore[arg-type]


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """One point in the fleet-strategy lattice.

    ``pool_pages`` is USABLE pages per replica at tp=1 (the engine's
    pool minus its scratch page); with ``pool_scales_with_tp`` (the
    head-sharded KV layout) a tp=k replica holds k× that."""

    replicas: int = 1
    tp: int = 1
    slots: int = 8
    pool_pages: int = 128
    queue_size: int = 64
    admission_limit: int = 128
    deadline_s: float = 120.0
    replica_inflight: int = 16
    placement: str = "affinity"      # affinity | least_loaded
    hedge_s: float = 0.0             # queue-escape bar: with measured
                                     # jitter in the profile, a request
                                     # pending longer than this moves to
                                     # a strictly less-loaded replica
                                     # (the duplicate-dispatch race,
                                     # resolved in the winner's favor);
                                     # without jitter nothing straggles
                                     # and the knob is a recorded no-op
    pool_scales_with_tp: bool = True

    def __post_init__(self):
        for f in ("replicas", "tp", "slots", "pool_pages", "queue_size",
                  "admission_limit", "replica_inflight"):
            if getattr(self, f) < 1:
                raise ValueError(f"fleet.{f} must be >= 1, got "
                                 f"{getattr(self, f)}")
        if self.placement not in ("affinity", "least_loaded"):
            raise ValueError(f"unknown placement {self.placement!r}; "
                             f"the simulator models 'affinity' and "
                             f"'least_loaded'")
        if self.deadline_s <= 0:
            raise ValueError("deadline_s must be positive")

    @property
    def chips(self) -> int:
        return self.replicas * self.tp

    @property
    def usable_pages(self) -> int:
        return self.pool_pages * (self.tp if self.pool_scales_with_tp
                                  else 1)

    def describe(self) -> str:
        parts = [f"replicas={self.replicas}"]
        if self.tp > 1:
            parts.append(f"tp={self.tp}")
        parts.append(f"slots={self.slots}")
        parts.append(f"pool={self.usable_pages}p")
        return ",".join(parts)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FleetPrediction:
    """What the simulator says a fleet does to a workload."""

    tokens_per_s: float
    latency_p50_s: float
    latency_p99_s: float
    queue_wait_p50_s: float
    queue_wait_p99_s: float
    completed: int
    shed: int
    deadlined: int
    shed_rate: float
    deadline_rate: float
    replica_utilization: float
    span_s: float
    hedged: int = 0                  # requests re-dispatched by the
                                     # hedge queue-escape (jitter runs)

    @property
    def loss_rate(self) -> float:
        return self.shed_rate + self.deadline_rate

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["loss_rate"] = self.loss_rate
        return d


class _Slot:
    __slots__ = ("req", "chunks_left", "tokens_left", "fresh_pages",
                 "reg_transfer", "group", "hit_pages")

    def __init__(self, req, chunks_left, tokens_left, fresh_pages,
                 group, hit_pages):
        self.req = req
        self.chunks_left = chunks_left
        self.tokens_left = tokens_left
        self.fresh_pages = fresh_pages
        self.reg_transfer = 0
        self.group = group
        self.hit_pages = hit_pages


class _SimReq:
    __slots__ = ("rec", "arrival", "budget", "admit_t", "finish_t",
                 "outcome", "placed_t")

    def __init__(self, rec):
        self.rec = rec
        self.arrival = rec.arrival_s
        # a parsed shed carries no token count (it never decoded) —
        # floor at 1 so the replayed fleet still pays its admission
        self.budget = max(int(rec.decode_tokens), 1)
        self.admit_t = None
        self.finish_t = None
        self.outcome = None
        self.placed_t = None        # last placed on a replica (hedge)


class _SimReplica:
    __slots__ = ("rid", "slots", "pending", "free_pages", "reg",
                 "inflight", "busy_s", "scheduled", "rr")

    def __init__(self, rid: int, pool_pages: int, slots: int):
        self.rid = rid
        self.slots: List[Optional[_Slot]] = [None] * slots
        self.pending: deque = deque()
        self.free_pages = pool_pages
        # prefix registry model: group -> {"pages": registered page
        # count (allocated, owned by the registry), "live": slots
        # currently sharing them}
        self.reg: Dict[str, dict] = {}
        self.inflight = 0
        self.busy_s = 0.0
        self.scheduled = False
        self.rr = -1


def simulate(workload: Workload, profile: ServeProfile,
             config: FleetConfig) -> FleetPrediction:
    """Replay ``workload`` through the fleet model.  Deterministic:
    same inputs, same prediction — jitter resampling runs off a fixed-
    seed in-module PRNG, not wall-clock entropy."""
    ps = profile.page_size
    step_s = profile.decode_step_for(config.tp)
    chunk_s = profile.prefill_chunk_for(config.tp)
    chunk_tokens = profile.chunk_tokens
    pool = config.usable_pages
    jit = profile.jitter
    jit_state = 0x9E3779B97F4A7C15
    hedged_n = 0

    def jitter_factor() -> float:
        # 64-bit LCG (Knuth MMIX constants) indexing the EMPIRICAL
        # distribution — same spread the trace measured, no parametric
        # assumption, and no numpy dependency to keep determinism
        # hostage to a library version
        nonlocal jit_state
        jit_state = (jit_state * 6364136223846793005
                     + 1442695040888963407) & 0xFFFFFFFFFFFFFFFF
        return jit[(jit_state >> 33) % len(jit)]

    reqs = [_SimReq(r) for r in workload.requests]
    if not reqs:
        return FleetPrediction(0.0, 0.0, 0.0, 0.0, 0.0, 0, 0, 0, 0.0,
                               0.0, 0.0, 0.0)
    reps = [_SimReplica(i, pool, config.slots)
            for i in range(config.replicas)]
    router_q: deque = deque()
    owner: Dict[str, int] = {}
    outstanding = 0
    seq = itertools.count()
    # event heap: (time, tiebreak, kind, payload) — "arr" before
    # "iter" at equal time via the monotonic tiebreak (arrivals were
    # pushed first)
    events: List[Tuple[float, int, str, object]] = []
    for sr in reqs:
        heapq.heappush(events, (sr.arrival, next(seq), "arr", sr))

    def total_pages(sr: _SimReq) -> int:
        return -(-(sr.rec.prompt_tokens + sr.budget) // ps)

    def shed(sr: _SimReq) -> None:
        sr.outcome = "shed"

    def rehedge(now: float) -> None:
        """The hedge policy, as the simulator can honor it: a request
        pending on one replica past ``hedge_s`` while a strictly
        less-loaded sibling has queue room is re-dispatched there —
        the router's duplicate-dispatch race, resolved in the winner's
        favor (optimistic: the loser's wasted work is not charged).
        Only meaningful under measured jitter; deterministic service
        never leaves a request stuck behind a straggler."""
        nonlocal hedged_n
        if config.hedge_s <= 0 or not jit:
            return
        for rep in reps:
            for sr in [s for s in rep.pending
                       if now - s.placed_t > config.hedge_s]:
                # the move must strictly improve balance (target ends
                # no more loaded than the source does) — that
                # monotonicity is what rules out hedge ping-pong
                tgt = [r2 for r2 in reps if r2 is not rep
                       and len(r2.pending) < config.queue_size
                       and r2.inflight + 2 <= rep.inflight]
                if not tgt:
                    break
                r2 = min(tgt, key=lambda r: (r.inflight, r.rid))
                rep.pending.remove(sr)
                rep.inflight -= 1
                r2.pending.append(sr)
                r2.inflight += 1
                sr.placed_t = now
                hedged_n += 1
                if sr.rec.prefix_group is not None:
                    owner[sr.rec.prefix_group] = r2.rid
                if not r2.scheduled:
                    r2.scheduled = True
                    heapq.heappush(events, (now, next(seq), "iter", r2))

    def dispatch(now: float) -> None:
        """The router's dispatch scan: place every queued request an
        eligible replica can take; shed what EVERY replica's queue has
        no room for (the Backpressure-relay contract — waiting there
        is a retry storm, not a queue)."""
        nonlocal outstanding
        rehedge(now)
        placed = []
        for sr in router_q:
            eligible = [rep for rep in reps
                        if rep.inflight < config.replica_inflight
                        and len(rep.pending) < config.queue_size]
            if not eligible:
                all_full = all(len(rep.pending) >= config.queue_size
                               for rep in reps)
                if all_full:
                    placed.append(sr)
                    shed(sr)
                    outstanding -= 1
                continue
            rep = None
            group = sr.rec.prefix_group
            if config.placement == "affinity" and group is not None:
                own = owner.get(group)
                if own is not None and reps[own] in eligible:
                    rep = reps[own]
            if rep is None:
                rep = min(eligible,
                          key=lambda r: (r.inflight, r.rid))
            if group is not None:
                owner[group] = rep.rid
            rep.pending.append(sr)
            rep.inflight += 1
            sr.placed_t = now
            placed.append(sr)
            if not rep.scheduled:
                rep.scheduled = True
                heapq.heappush(events, (now, next(seq), "iter", rep))
        for sr in placed:
            router_q.remove(sr)

    def admit(rep: _SimReplica, now: float) -> None:
        """The engine's admission: FIFO head-of-line, page math,
        prefix hits, registry eviction."""
        nonlocal outstanding
        while rep.pending:
            free_idx = next((i for i, s in enumerate(rep.slots)
                             if s is None), None)
            if free_idx is None:
                return
            sr = rep.pending[0]
            need_total = total_pages(sr)
            if need_total > pool:
                # the engine's submit guard: could never be admitted
                rep.pending.popleft()
                rep.inflight -= 1
                outstanding -= 1
                shed(sr)
                continue
            group = sr.rec.prefix_group
            prompt_pages = sr.rec.prompt_tokens // ps
            hit = 0
            if group is not None and group in rep.reg:
                hit = min(rep.reg[group]["pages"], prompt_pages)
            elif group is None and sr.rec.prefix_tokens:
                # parsed trace: replay the measured share depth
                hit = min(sr.rec.prefix_tokens // ps, prompt_pages)
            fresh = need_total - hit
            if fresh > rep.free_pages:
                # evict registry-only pages until the admit fits —
                # cached prefixes yield to live traffic, but the
                # `hit` pages this admit is sharing are HELD (the
                # engine holds shares before evicting): the admitted
                # group's chain may only be truncated BEYOND the held
                # depth.  Router-side ownership intentionally survives
                # an eviction — like the real tier, a stale owner
                # costs a registry miss + re-prefill, not a reroute.
                if group is not None:
                    e = rep.reg.get(group)
                    if e is not None and e["live"] == 0 \
                            and e["pages"] > hit:
                        rep.free_pages += e["pages"] - hit
                        e["pages"] = hit
                        if hit == 0:
                            del rep.reg[group]
                for g in [g for g, e in rep.reg.items()
                          if e["live"] == 0 and g != group]:
                    if rep.free_pages >= fresh:
                        break
                    rep.free_pages += rep.reg[g]["pages"]
                    del rep.reg[g]
                if fresh > rep.free_pages:
                    return      # head-of-line wait for a retire
            rep.pending.popleft()
            rep.free_pages -= fresh
            if hit > 0 and group is not None and group in rep.reg:
                rep.reg[group]["live"] += 1
            remaining = sr.rec.prompt_tokens - hit * ps
            chunks = -(-remaining // chunk_tokens) if remaining > 0 \
                else 0
            # the engine's last prefill chunk emits the FIRST token, so
            # a chunked request pays budget − 1 decode steps; the
            # full-prefix (COW) path has no prefill and decodes all of
            # them (its first token comes out of a decode step)
            steps = sr.budget - (1 if chunks > 0 else 0)
            rep.slots[free_idx] = _Slot(sr, chunks, steps, fresh,
                                        group, hit)
            sr.admit_t = now

    def retire(rep: _SimReplica, idx: int, now: float) -> None:
        nonlocal outstanding
        slot = rep.slots[idx]
        rep.slots[idx] = None
        rep.free_pages += slot.fresh_pages - slot.reg_transfer
        if slot.group is not None and slot.group in rep.reg \
                and slot.hit_pages:
            rep.reg[slot.group]["live"] -= 1
        rep.inflight -= 1
        outstanding -= 1
        sr = slot.req
        sr.finish_t = now
        sr.outcome = ("deadline"
                      if now - sr.arrival > config.deadline_s
                      else "complete")

    def iteration(rep: _SimReplica, now: float) -> None:
        rep.scheduled = False
        admit(rep, now)
        live = [(i, s) for i, s in enumerate(rep.slots) if s is not None]
        prefilling = [(i, s) for i, s in live if s.chunks_left > 0]
        decoding = [(i, s) for i, s in live
                    if s.chunks_left == 0 and s.tokens_left > 0]
        if not prefilling and not decoding:
            return              # idle until the next dispatch wakes it
        dt = profile.overhead_s
        if prefilling:
            dt += chunk_s * (jitter_factor() if jit else 1.0)
        if decoding:
            dt += step_s * (jitter_factor() if jit else 1.0)
        rep.busy_s += dt
        t2 = now + dt
        if prefilling:
            # ONE chunk per iteration, round-robin — the engine's
            # head-of-line-bounding schedule
            i, s = next(((i, s) for i, s in prefilling if i > rep.rr),
                        prefilling[0])
            rep.rr = i
            s.chunks_left -= 1
            if s.chunks_left == 0:
                if s.group is not None and s.group not in rep.reg:
                    # prefill complete: register the group's full
                    # prompt pages; the registry takes co-ownership
                    # (they stay allocated past this slot's retire,
                    # until evicted)
                    reg_pages = min(s.req.rec.prompt_tokens // ps,
                                    s.fresh_pages)
                    if reg_pages > 0:
                        rep.reg[s.group] = {"pages": reg_pages,
                                            "live": 1}
                        s.reg_transfer = reg_pages
                        s.hit_pages = reg_pages  # dropped at retire
                if s.tokens_left == 0:
                    # a 1-token budget finishes AT the prefill (the
                    # chunk's sampled token is the whole answer)
                    retire(rep, i, t2)
        for i, s in decoding:
            s.tokens_left -= 1
            if s.tokens_left == 0:
                retire(rep, i, t2)
        # dispatch may itself schedule THIS replica's next iteration
        # (fresh work placed on it) — check scheduled after, or a
        # double-pushed event would run two iterations at one
        # timestamp, i.e. free compute
        dispatch(t2)
        if not rep.scheduled and (rep.pending or any(
                s is not None for s in rep.slots)):
            rep.scheduled = True
            heapq.heappush(events, (t2, next(seq), "iter", rep))

    while events:
        now, _, kind, payload = heapq.heappop(events)
        if kind == "arr":
            sr = payload
            if outstanding >= config.admission_limit:
                shed(sr)
                continue
            outstanding += 1
            router_q.append(sr)
            dispatch(now)
        else:
            iteration(payload, now)

    # aggregate
    completes = [sr for sr in reqs if sr.outcome == "complete"]
    shed_n = sum(1 for sr in reqs if sr.outcome == "shed")
    dead_n = sum(1 for sr in reqs if sr.outcome == "deadline")
    total = len(reqs)
    if completes:
        span = (max(sr.finish_t for sr in completes)
                - min(sr.arrival for sr in completes))
        tokens = sum(sr.budget for sr in completes)
        lat = sorted(sr.finish_t - sr.arrival for sr in completes)
        wait = sorted(sr.admit_t - sr.arrival for sr in completes)
        full_span = (max(sr.finish_t or sr.arrival for sr in reqs)
                     - min(sr.arrival for sr in reqs))
        return FleetPrediction(
            tokens_per_s=tokens / span if span > 0 else 0.0,
            latency_p50_s=percentile(lat, 50.0),
            latency_p99_s=percentile(lat, 99.0),
            queue_wait_p50_s=percentile(wait, 50.0),
            queue_wait_p99_s=percentile(wait, 99.0),
            completed=len(completes), shed=shed_n, deadlined=dead_n,
            shed_rate=shed_n / total, deadline_rate=dead_n / total,
            replica_utilization=(sum(r.busy_s for r in reps)
                                 / (len(reps) * full_span))
            if full_span > 0 else 0.0,
            span_s=span, hedged=hedged_n)
    return FleetPrediction(0.0, 0.0, 0.0, 0.0, 0.0, 0, shed_n, dead_n,
                           shed_n / total if total else 0.0,
                           dead_n / total if total else 0.0, 0.0, 0.0,
                           hedged=hedged_n)


# ---------------------------------------------------------------------------
# what-if answers
# ---------------------------------------------------------------------------

def replicas_for(workload: Workload, profile: ServeProfile,
                 config: FleetConfig, target_rps: float,
                 slo_p99_s: float, *, max_replicas: int = 64,
                 loss_bar: float = DEFAULT_LOSS_BAR
                 ) -> Tuple[Optional[int], List[Tuple[int,
                                                      FleetPrediction]]]:
    """Smallest replica count serving the workload's SHAPE at
    ``target_rps`` with p99 latency within the SLO and loss (sheds +
    deadline failures) under ``loss_bar``.  Returns (count-or-None,
    every (replicas, prediction) evaluated)."""
    from dtf_tpu.plan.serve_trace import scale_workload
    w = scale_workload(workload, target_rps)
    evaluated: List[Tuple[int, FleetPrediction]] = []
    for n in range(1, max_replicas + 1):
        pred = simulate(w, profile,
                        dataclasses.replace(config, replicas=n))
        evaluated.append((n, pred))
        if (pred.completed and pred.latency_p99_s <= slo_p99_s
                and pred.loss_rate <= loss_bar):
            return n, evaluated
    return None, evaluated


def rank_tp_vs_replicas(workload: Workload, profile: ServeProfile,
                        config: FleetConfig, chips: int, *,
                        loss_bar: float = DEFAULT_LOSS_BAR
                        ) -> List[Tuple[FleetConfig, FleetPrediction]]:
    """At a fixed chip budget, rank every tp × replicas split
    (tp ∈ powers of two dividing ``chips``): configs under the loss
    bar first, by p99 latency, then by delivered tokens/s.  The trade
    the model captures: TP cuts per-step latency (Amdahl) and grows
    the per-replica page pool, MORE REPLICAS add independent queues
    and admission capacity."""
    if chips < 1:
        raise ValueError(f"chips must be >= 1, got {chips}")
    out: List[Tuple[FleetConfig, FleetPrediction]] = []
    tp = 1
    while tp <= chips:
        if chips % tp == 0:
            cfg = dataclasses.replace(config, tp=tp,
                                      replicas=chips // tp)
            out.append((cfg, simulate(workload, profile, cfg)))
        tp *= 2
    out.sort(key=lambda cp: (cp[1].loss_rate > loss_bar,
                             cp[1].latency_p99_s,
                             -cp[1].tokens_per_s))
    return out


@dataclasses.dataclass(frozen=True)
class CostRankedConfig:
    """One row of :func:`rank_cost_per_token`."""

    config: FleetConfig
    prediction: FleetPrediction
    meets_slo: bool
    usd_per_mtoken: float
    usd_per_hour: float

    def to_dict(self) -> dict:
        finite = self.usd_per_mtoken != float("inf")
        return {"config": self.config.to_dict(),
                "prediction": self.prediction.to_dict(),
                "meets_slo": self.meets_slo,
                # None, not Infinity: the artifact stays strict JSON
                "usd_per_mtoken": (self.usd_per_mtoken if finite
                                   else None),
                "usd_per_hour": self.usd_per_hour}


def rank_cost_per_token(workload: Workload, profile: ServeProfile,
                        config: FleetConfig, chips: int,
                        chip_cost_per_hour: float, slo_p99_s: float, *,
                        loss_bar: float = DEFAULT_LOSS_BAR,
                        evaluated: Optional[
                            List[Tuple[FleetConfig,
                                       FleetPrediction]]] = None
                        ) -> List[CostRankedConfig]:
    """Rank every tp × replicas split of a chip budget by **$/token at
    the SLO** — the capacity-sim follow-on the MFU ledger enables: the
    ledger knows chips and achieved throughput, so feasibility alone
    is no longer the interesting verdict; the cheapest config that
    still meets the p99 SLO and the loss bar is.

    A fleet's dollar rate is ``chips × chip_cost_per_hour`` (every
    ranked split uses the full budget, but the rate is computed per
    config so partial splits of non-power-of-two budgets price
    honestly); delivered tokens/s comes from the simulator, so
    $/Mtoken = rate / (3600 · tokens_per_s) · 1e6.  Configs that MISS
    the SLO or the loss bar rank strictly below every config that
    meets them — a cheap config that sheds is not a bargain — ordered
    among themselves by $/Mtoken for the "what would it take" view.

    ``evaluated`` reuses :func:`rank_tp_vs_replicas`' (config,
    prediction) pairs when the caller already simulated the splits
    (the CLI runs both what-ifs on one pass); None simulates here."""
    if chips < 1:
        raise ValueError(f"chips must be >= 1, got {chips}")
    if chip_cost_per_hour <= 0:
        raise ValueError(f"chip_cost_per_hour must be positive, got "
                         f"{chip_cost_per_hour}")
    if slo_p99_s <= 0:
        raise ValueError(f"slo_p99_s must be positive, got {slo_p99_s}")
    if evaluated is None:
        evaluated = rank_tp_vs_replicas(workload, profile, config,
                                        chips, loss_bar=loss_bar)
    rows: List[CostRankedConfig] = []
    for cfg, pred in evaluated:
        meets = bool(pred.completed
                     and pred.latency_p99_s <= slo_p99_s
                     and pred.loss_rate <= loss_bar)
        rate = cfg.chips * chip_cost_per_hour
        usd_mtok = (rate / 3600.0 / pred.tokens_per_s * 1e6
                    if pred.tokens_per_s > 0 else float("inf"))
        rows.append(CostRankedConfig(cfg, pred, meets, usd_mtok, rate))
    rows.sort(key=lambda r: (not r.meets_slo, r.usd_per_mtoken,
                             r.prediction.latency_p99_s))
    return rows


def pool_vs_shed(workload: Workload, profile: ServeProfile,
                 config: FleetConfig, pool_sizes: Sequence[int], *,
                 loss_bar: float = DEFAULT_LOSS_BAR
                 ) -> Tuple[Optional[int],
                            List[Tuple[int, FleetPrediction]]]:
    """Page-pool sizing: predictions for each candidate USABLE
    per-replica pool size (at tp=1), plus the smallest one whose loss
    rate stays under the bar.  Smaller pools convert directly into
    sheds/waits through the admission math — this is the provisioning
    curve."""
    rows = [(int(p), simulate(workload, profile,
                              dataclasses.replace(config,
                                                  pool_pages=int(p))))
            for p in sorted(pool_sizes)]
    best = next((p for p, pred in rows
                 if pred.completed and pred.loss_rate <= loss_bar), None)
    return best, rows


@dataclasses.dataclass(frozen=True)
class PoolSplitRow:
    """One row of :func:`pool_split`.  ``prefill_replicas == 0`` is the
    colocated baseline: ``decode`` then holds the whole tier's
    prediction and ``prefill`` is None (no second pool exists)."""

    prefill_replicas: int
    decode_replicas: int
    migrate_chunk_s: float
    decode: FleetPrediction
    prefill: Optional[FleetPrediction] = None

    @property
    def is_colocated(self) -> bool:
        return self.prefill_replicas == 0

    @property
    def loss_rate(self) -> float:
        """A request is lost if EITHER pool loses it."""
        if self.prefill is None:
            return self.decode.loss_rate
        return max(self.decode.loss_rate, self.prefill.loss_rate)

    def describe(self) -> str:
        if self.is_colocated:
            return f"colocated ({self.decode_replicas} replicas)"
        return (f"{self.prefill_replicas}p:"
                f"{self.decode_replicas}d split")

    def to_dict(self) -> dict:
        return {"prefill_replicas": self.prefill_replicas,
                "decode_replicas": self.decode_replicas,
                "migrate_chunk_s": self.migrate_chunk_s,
                "loss_rate": self.loss_rate,
                "decode": self.decode.to_dict(),
                "prefill": (self.prefill.to_dict()
                            if self.prefill is not None else None)}


def pool_split(workload: Workload, profile: ServeProfile,
               config: FleetConfig, chips: int, *,
               page_bytes: int = 1 << 20, wire_gbps: float = 10.0,
               wire_latency_s: float = 0.002,
               loss_bar: float = DEFAULT_LOSS_BAR
               ) -> Tuple[Optional[PoolSplitRow], List[PoolSplitRow]]:
    """Disaggregation what-if: at a fixed chip budget, colocated vs
    every prefill:decode replica split (tp held at ``config.tp``).

    The split is modeled as two independent fleets fed the same
    arrival process:

      prefill pool — the workload with every decode budget cut to the
          single token prefill emits (the chain then LEAVES: finished
          prefills migrate out, so the pool's only decode work is
          first tokens).
      decode pool  — the full workload, with prefill chunks replaced
          by MIGRATION chunks: the same prompt pages arrive over the
          fabric at ``wire_gbps`` (decimal Gbit/s) plus a
          ``wire_latency_s`` window round-trip per chunk — the cost
          shape of ``serve/migrate.py``'s windowed ``page_fetch``
          protocol.  Prefix affinity still applies (a shared prefix
          migrates once, later requests hit the registry).

    End-to-end latency does not compose across the two simulations
    (each pool queues independently), so the ranking criterion is the
    DECODE pool's p99 — time-between-tokens is what disaggregation
    buys; the prefill pool only has to stay feasible under the loss
    bar.  ``best`` is the feasible split with the lowest decode p99
    that strictly beats colocated p99 at equal chips, or None when
    colocated wins (the honest verdict: migration is not free).

    Returns ``(best, rows)`` — ``rows[0]`` is the colocated
    baseline."""
    if chips < 2:
        raise ValueError(f"pool_split needs chips >= 2 (one replica "
                         f"cannot split), got {chips}")
    if chips % config.tp != 0:
        raise ValueError(f"chips ({chips}) must be a multiple of "
                         f"config.tp ({config.tp}) — the split is in "
                         f"whole replicas")
    if page_bytes < 1 or wire_gbps <= 0 or wire_latency_s < 0:
        raise ValueError("page_bytes must be >= 1, wire_gbps positive, "
                         "wire_latency_s non-negative")
    n = chips // config.tp
    if n < 2:
        raise ValueError(f"chips/tp leaves {n} replica(s) — nothing "
                         f"to split")
    # one chunk-equivalent of prompt pages over the fabric: payload
    # time at wire bandwidth plus one window round-trip
    wire_bytes_per_s = wire_gbps * 1e9 / 8.0
    mig_chunk_s = (wire_latency_s
                   + (profile.chunk_tokens / profile.page_size)
                   * page_bytes / wire_bytes_per_s)
    colocated = simulate(workload, profile,
                         dataclasses.replace(config, replicas=n))
    rows = [PoolSplitRow(0, n, 0.0, colocated)]
    prefill_w = Workload(
        [dataclasses.replace(r, decode_tokens=1)
         for r in workload.requests],
        workload.duration_s, workload.source + ":prefill_pool",
        workload.skipped_no_trace)
    decode_profile = dataclasses.replace(profile,
                                         prefill_chunk_s=mig_chunk_s)
    for p in range(1, n):
        d = n - p
        pre = simulate(prefill_w, profile,
                       dataclasses.replace(config, replicas=p))
        dec = simulate(workload, decode_profile,
                       dataclasses.replace(config, replicas=d))
        rows.append(PoolSplitRow(p, d, mig_chunk_s, dec, pre))
    feasible = [r for r in rows[1:]
                if r.decode.completed and r.loss_rate <= loss_bar
                and r.decode.latency_p99_s < colocated.latency_p99_s]
    best = min(feasible,
               key=lambda r: (r.decode.latency_p99_s,
                              -r.decode.tokens_per_s),
               default=None)
    return best, rows


def measured_tp_comm_frac(t_base: float, t_scaled: float, *,
                          tp_base: int = 1, tp_scaled: int = 2
                          ) -> float:
    """Solve the Amdahl split for ``tp_comm_frac`` from two MEASURED
    decode-step times instead of trusting the documented default:
    ``t(tp) = t(base) · (f + (1 − f) · base/tp)`` gives
    ``f = (t_scaled/t_base − base/scaled) / (1 − base/scaled)``.

    Clamped into the profile's valid domain: a super-linear speedup
    measures as 0.0 (all compute), a SLOWDOWN under TP clamps at 0.95
    rather than rejecting — the planner should still rank with the
    pessimistic number, not die on a noisy box."""
    if t_base <= 0 or t_scaled <= 0:
        raise ValueError("measured step times must be positive")
    if tp_scaled <= tp_base:
        raise ValueError(f"tp_scaled ({tp_scaled}) must exceed "
                         f"tp_base ({tp_base})")
    share = tp_base / tp_scaled
    frac = (t_scaled / t_base - share) / (1.0 - share)
    return min(max(frac, 0.0), 0.95)


# ---------------------------------------------------------------------------
# calibration (predicted vs measured, PR-5 shape)
# ---------------------------------------------------------------------------

def calibration_ratios(measured: dict, pred: FleetPrediction,
                       registry=None) -> dict:
    """Predicted/measured ratios for the two headline numbers, exported
    as gauges the way plan_main's ``plan_step_time_ratio`` is:

      plan_serve_predicted_tokens_per_s / plan_serve_measured_tokens_per_s
      plan_serve_tokens_ratio
      plan_serve_predicted_p99_s / plan_serve_measured_p99_s
      plan_serve_p99_ratio

    ``measured`` is :func:`~dtf_tpu.plan.serve_trace.measured_stats`
    output.  Raises ValueError when the measured run has nothing to
    calibrate against (no completed requests)."""
    from dtf_tpu.obs.registry import default_registry
    if not measured.get("completed") or not measured.get("tokens_per_s"):
        raise ValueError("measured workload has no completed requests — "
                         "nothing to calibrate against")
    if not pred.completed:
        raise ValueError("prediction completed no requests — the model "
                         "shed everything the real run served")
    reg = registry if registry is not None else default_registry()
    tokens_ratio = pred.tokens_per_s / measured["tokens_per_s"]
    p99_ratio = (pred.latency_p99_s / measured["latency_p99_s"]
                 if measured["latency_p99_s"] > 0 else float("inf"))
    reg.gauge("plan_serve_predicted_tokens_per_s",
              unit="tokens/s").set(pred.tokens_per_s)
    reg.gauge("plan_serve_measured_tokens_per_s",
              unit="tokens/s").set(measured["tokens_per_s"])
    reg.gauge("plan_serve_tokens_ratio").set(tokens_ratio)
    reg.gauge("plan_serve_predicted_p99_s",
              unit="s").set(pred.latency_p99_s)
    reg.gauge("plan_serve_measured_p99_s",
              unit="s").set(measured["latency_p99_s"])
    reg.gauge("plan_serve_p99_ratio").set(p99_ratio)
    return {"tokens_ratio": tokens_ratio, "p99_ratio": p99_ratio}


def ratios_within(ratios: dict, tolerance: float) -> bool:
    """The calibration bar: every ratio inside [1/tol, tol]."""
    return all(1.0 / tolerance <= r <= tolerance
               for r in ratios.values())
