"""Plan → config compilation: resolve ``--plan auto|<file>`` into the
EXISTING parallelism flags.

The planner deliberately owns no runtime of its own — a chosen
:class:`Plan` compiles down to exactly the flags an operator would have
typed (`--model_parallelism`, `--seq_parallelism`,
`--optimizer_sharding`, `--grad_accum_steps` / `--num_microbatches`,
`--remat`, `--num_devices`), so a plan-selected run is bit-identical to
the same configuration set by hand (tests/test_plan.py asserts this).
The flags a plan owns must be at their defaults when ``--plan`` is
given: a hand-set `--model_parallelism 4` silently overridden by a plan
(or vice versa) is exactly the folklore-vs-model ambiguity this
subsystem exists to remove.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from dtf_tpu.plan.cost_model import Plan, check_plan, predict
from dtf_tpu.plan.mesh_spec import MeshSpec, mesh_spec
from dtf_tpu.plan.model_stats import ModelStats, characterize
from dtf_tpu.plan.search import best_plan

log = logging.getLogger("dtf_tpu")

# Flags a plan compiles into, with the defaults they must still hold
# when --plan is given (conflict = loud error, never silent override)
PLAN_OWNED_FLAGS = {
    "model_parallelism": 1,
    "seq_parallelism": 1,
    "optimizer_sharding": False,
    "zero_stage": 0,
    "grad_accum_steps": 1,
    "num_microbatches": None,
    "remat": False,
    "remat_policy": None,
}


def dtype_bytes_of(cfg) -> int:
    return 2 if cfg.dtype in ("bf16", "bfloat16", "fp16", "float16") else 4


def stats_for_config(cfg) -> ModelStats:
    """Characterize the model a config would build, at the config's
    shapes (seq_len override, num_classes override, compute dtype)."""
    from dtf_tpu.data import get_dataset_spec

    model_name = "trivial" if cfg.use_trivial_model else cfg.model
    seq_len = None
    if cfg.dataset:
        spec = get_dataset_spec(cfg.dataset)
        if spec.is_sequence:
            seq_len = cfg.seq_len or spec.seq_len
    return characterize(model_name, seq_len=seq_len,
                        num_classes=cfg.num_classes,
                        dtype_bytes=dtype_bytes_of(cfg))


def apply_plan(cfg, plan: Plan):
    """Compile a plan into config flags.  Raises when a plan-owned flag
    was hand-set (ambiguous intent) or when an explicit --num_devices
    contradicts the plan's device count.  The returned config has
    ``plan=""`` — it IS the hand-flag form."""
    conflicts = [k for k, default in PLAN_OWNED_FLAGS.items()
                 if getattr(cfg, k) != default]
    if conflicts:
        raise ValueError(
            f"--plan conflicts with hand-set flags {conflicts}: a plan "
            f"compiles into exactly these flags — drop them or drop "
            f"--plan")
    if cfg.num_devices is not None and cfg.num_devices != plan.num_devices:
        raise ValueError(
            f"--num_devices {cfg.num_devices} contradicts the plan's "
            f"{plan.num_devices} devices ({plan.describe()})")
    is_pipeline = cfg.model.startswith("pipeline_transformer")
    kw = dict(
        plan="",
        num_devices=plan.num_devices,
        model_parallelism=plan.model_axis_size,
        seq_parallelism=plan.seq,
        # stage 1 keeps compiling into the historical shorthand flag;
        # stages 2/3 into --zero_stage (the two are mutually exclusive
        # by Config validation)
        optimizer_sharding=plan.zero == 1,
        zero_stage=plan.zero if plan.zero >= 2 else 0,
        remat=plan.remat,
    )
    if is_pipeline:
        kw["num_microbatches"] = plan.microbatch
    elif plan.microbatch > 1:
        kw["grad_accum_steps"] = plan.microbatch
    return cfg.replace(**kw)


def plan_from_config(cfg, num_devices: int) -> Plan:
    """The plan a hand-flagged config already describes (the inverse of
    apply_plan) — what the calibration loop predicts for a run
    configured without --plan.

    Two deliberate approximations: a pipeline config with
    ``num_microbatches`` unset mirrors the runner's auto-pick
    (M = 4·pp halved until it divides the per-shard batch —
    cli/runner.py), and ``--remat_policy dots`` maps to plain
    ``remat=True`` (the cost model has no selective-remat point; it
    over-counts dots' recompute and under-counts its saved bytes)."""
    maxis = max(cfg.model_parallelism, 1)
    is_pipeline = cfg.model.startswith("pipeline_transformer")
    sp = max(cfg.seq_parallelism, 1)
    if num_devices % (maxis * sp):
        raise ValueError(
            f"{num_devices} devices not divisible by "
            f"model_parallelism×seq_parallelism = {maxis * sp}")
    if is_pipeline and cfg.num_microbatches is None:
        per_shard = cfg.batch_size // max(num_devices // (maxis * sp), 1)
        micro = 4 * maxis
        while micro > 1 and per_shard % micro:
            micro //= 2
    else:
        micro = (cfg.num_microbatches if is_pipeline
                 else cfg.grad_accum_steps) or 1
    return Plan(data=num_devices // (maxis * sp),
                model=1 if is_pipeline else maxis,
                pipeline=maxis if is_pipeline else 1,
                seq=sp, zero=cfg.zero_stage_effective,
                microbatch=max(int(micro), 1),
                remat=bool(cfg.remat or cfg.remat_policy))


def load_plan_file(path: str) -> Plan:
    """A plan from a JSON file: a bare plan object, a ``{"plan": …}``
    wrapper, or a ranked artifact (``{"plans": [...]}`` — the first
    feasible entry wins)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "plans" in doc:
        for entry in doc["plans"]:
            if entry.get("feasible", True):
                return Plan.from_dict(entry["plan"])
        raise ValueError(f"ranked plan artifact {path!r} contains no "
                         f"feasible plan")
    if isinstance(doc, dict) and "plan" in doc:
        doc = doc["plan"]
    if not isinstance(doc, dict):
        raise ValueError(f"plan file {path!r}: expected a JSON object, "
                         f"got {type(doc).__name__}")
    return Plan.from_dict(doc)


def resolve_plan(cfg, mesh: Optional[MeshSpec] = None):
    """Resolve ``cfg.plan`` ("auto" or a plan-file path) into concrete
    config flags.  No-op when the flag is empty.  Infeasible or invalid
    plans are rejected loudly — a plan that would OOM must die here,
    not twenty minutes into compilation on a pod."""
    if not cfg.plan:
        return cfg
    if cfg.distribution_strategy in ("horovod", "parameter_server"):
        raise ValueError(
            f"--plan targets the SPMD strategies (batch_size is the "
            f"global batch); --distribution_strategy "
            f"{cfg.distribution_strategy} scales batch per replica — "
            f"set the parallelism flags by hand")
    stats = stats_for_config(cfg)
    # an explicit --num_devices bounds the LIVE mesh (planning a subset
    # of the attached chips); explicit presets/descriptors ignore it —
    # apply_plan's contradiction check still fires for those
    mesh = mesh or mesh_spec(cfg.plan_mesh, live_devices=cfg.num_devices)
    if not cfg.plan_mesh and cfg.num_devices is not None \
            and mesh.num_hosts > 1:
        raise ValueError(
            "--plan with --num_devices on a multi-host run is ambiguous "
            "(num_devices means per-process local chips under mirrored, "
            "a global truncation otherwise) — pass an explicit "
            "--plan_mesh descriptor instead")
    if cfg.plan == "auto":
        if cfg.plan_cache:
            # memoized lattice: launcher restarts and repeated resolves
            # skip the search; the pick + loud-failure logic is shared.
            # overlap_frac defaults to AUTO here: a prior `plan_main
            # --calibrate` against this cache persisted the MEASURED
            # overlap fraction for (workload, mesh), and resolution
            # uses it without an operator in the loop (plan/cache.py)
            from dtf_tpu.plan.cache import cached_search
            from dtf_tpu.plan.search import best_from_ranked
            ranked_list, _ = cached_search(
                cfg.plan_cache, stats, mesh, cfg.batch_size,
                optimizer=cfg.optimizer)
            ranked = best_from_ranked(ranked_list, stats, mesh,
                                      cfg.batch_size)
        else:
            ranked = best_plan(stats, mesh, cfg.batch_size,
                               optimizer=cfg.optimizer)
        plan, cost = ranked.plan, ranked.cost
        log.info(
            "plan auto (%s, %d devices): %s — predicted %.1f ms/step, "
            "peak %.2f GiB/device (budget %.2f)", mesh.name,
            mesh.num_devices, plan.describe(), cost.step_time_s * 1e3,
            cost.peak_bytes / 2 ** 30, cost.hbm_budget_bytes / 2 ** 30)
    else:
        plan = load_plan_file(cfg.plan)
        violations = check_plan(plan, stats, mesh, cfg.batch_size)
        if violations:
            raise ValueError(
                f"plan {plan.describe()} from {cfg.plan!r} is invalid "
                f"for {stats.model} on {mesh.name}: "
                f"{'; '.join(violations)}")
        cost = predict(plan, stats, mesh, cfg.batch_size,
                       optimizer=cfg.optimizer)
        if not cost.feasible:
            raise ValueError(
                f"plan {plan.describe()} from {cfg.plan!r} is "
                f"memory-INFEASIBLE on {mesh.name}: predicted peak "
                f"{cost.peak_bytes / 2**30:.2f} GiB/device exceeds the "
                f"budget {cost.hbm_budget_bytes / 2**30:.2f} GiB "
                f"({mesh.hbm_bytes / 2**30:.0f} GiB HBM × "
                f"{cost.hbm_budget_bytes / mesh.hbm_bytes:.0%})")
        log.info(
            "plan %s from %s: predicted %.1f ms/step, peak %.2f "
            "GiB/device", plan.describe(), cfg.plan,
            cost.step_time_s * 1e3, cost.peak_bytes / 2 ** 30)
    import jax
    attached = jax.device_count()
    if plan.num_devices > attached:
        # without this, runtime/mesh.initialize silently truncates the
        # device list and the run executes a DIFFERENT parallelization
        # than the one planned (e.g. a 4x4-pod plan degrading to dp=2
        # on an 8-device box) — the opposite of "plans die loudly"
        raise ValueError(
            f"plan {plan.describe()} targets {plan.num_devices} devices "
            f"({mesh.name} mesh) but only {attached} are attached — a "
            f"plan for a larger simulated mesh can be ranked with "
            f"plan_main, not run here")
    return apply_plan(cfg, plan)
