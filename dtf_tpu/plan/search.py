"""Feasible-plan search: enumerate the strategy lattice, keep what
passes the hard constraints, rank what fits the HBM budget by predicted
step time, and emit a ranked JSON artifact.

The lattice is small by construction — axis sizes are factorizations of
the device count, microbatch counts are powers of two dividing the
per-replica batch — so exhaustive enumeration beats anything cleverer:
a 4-host × 4-device pod's full lattice is a few hundred plans and ranks
in milliseconds on a laptop.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Iterator, List, Optional, Tuple

from dtf_tpu.plan.cost_model import (DEFAULT_OVERLAP_FRAC, HBM_FRACTION,
                                     Plan, PlanCost, check_plan, predict)
from dtf_tpu.plan.mesh_spec import MeshSpec
from dtf_tpu.plan.model_stats import ModelStats

MAX_MICROBATCH = 64


@dataclasses.dataclass(frozen=True)
class RankedPlan:
    plan: Plan
    cost: PlanCost
    violations: Tuple[str, ...] = ()

    @property
    def feasible(self) -> bool:
        return not self.violations and self.cost.feasible

    def to_dict(self) -> dict:
        return {"plan": self.plan.to_dict(), "predicted": self.cost.to_dict(),
                "feasible": self.feasible,
                "violations": list(self.violations)}


def _factorizations(n: int, ways: int) -> Iterator[Tuple[int, ...]]:
    """All ordered tuples of `ways` positive ints whose product is n."""
    if ways == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, ways - 1):
                yield (d,) + rest


def enumerate_plans(stats: ModelStats, mesh: MeshSpec, global_batch: int
                    ) -> Iterator[Plan]:
    """Every plan in the lattice that passes the hard constraints
    (devices, divisibility, family capabilities).  Memory feasibility
    is NOT filtered here — search() ranks and tags it, so the artifact
    can show near-miss plans with their predicted overage."""
    n = mesh.num_devices
    micro_opts = [m for m in
                  itertools.takewhile(lambda m: m <= MAX_MICROBATCH,
                                      (2 ** i for i in range(32)))
                  if m <= max(global_batch, 1)]
    seen = set()
    for data, seq, maxis in _factorizations(n, 3):
        # the 'model' mesh axis carries EITHER tensor ways OR pipeline
        # stages (runner.py maps pipeline families onto the same axis)
        axis_roles = [(maxis, 1)]
        if maxis > 1 and stats.supports_pipeline:
            axis_roles = [(1, maxis)]
        for model, pipeline in axis_roles:
            for zero, micro, remat in itertools.product(
                    (0, 1, 2, 3), micro_opts,
                    (False, True) if stats.supports_remat else (False,)):
                try:
                    plan = Plan(data=data, model=model, seq=seq,
                                pipeline=pipeline, zero=zero,
                                microbatch=micro, remat=remat)
                except ValueError:
                    continue
                if plan in seen:
                    continue
                seen.add(plan)
                if not check_plan(plan, stats, mesh, global_batch):
                    yield plan


def search(stats: ModelStats, mesh: MeshSpec, global_batch: int,
           optimizer: str = "sgd", hbm_fraction: float = HBM_FRACTION,
           device_flops: Optional[float] = None,
           overlap_frac: float = DEFAULT_OVERLAP_FRAC) -> List[RankedPlan]:
    """Rank the whole valid lattice: feasible plans first by predicted
    step time, then infeasible ones by how far over budget they are
    (the artifact keeps them so an operator can see WHY a tempting
    plan was rejected)."""
    ranked = [RankedPlan(plan, predict(plan, stats, mesh, global_batch,
                                       optimizer=optimizer,
                                       hbm_fraction=hbm_fraction,
                                       device_flops=device_flops,
                                       overlap_frac=overlap_frac))
              for plan in enumerate_plans(stats, mesh, global_batch)]
    # feasible first by predicted step time; the analytic times
    # quantize so ties are common — break them toward the FEWEST
    # microbatches (accumulation/pipelining chunks carry unmodeled
    # per-chunk dispatch overhead, so at equal predicted time deeper
    # splitting is pure downside), then toward the lower predicted
    # peak (memory headroom is free insurance)
    return sorted(ranked, key=lambda r: (not r.feasible,
                                         (r.cost.step_time_s,
                                          r.plan.microbatch,
                                          r.cost.peak_bytes)
                                         if r.feasible
                                         else (r.cost.peak_bytes, 0, 0.0)))


def best_plan(stats: ModelStats, mesh: MeshSpec, global_batch: int,
              optimizer: str = "sgd") -> RankedPlan:
    """The `--plan auto` resolution: the fastest feasible plan, or a
    loud error naming the smallest predicted overage when nothing
    fits."""
    return best_from_ranked(search(stats, mesh, global_batch,
                                   optimizer=optimizer),
                            stats, mesh, global_batch)


def best_from_ranked(ranked: List[RankedPlan], stats: ModelStats,
                     mesh: MeshSpec, global_batch: int) -> RankedPlan:
    """best_plan over an already-ranked lattice (the plan-cache path
    feeds memoized rankings through the same pick + loud-failure
    logic)."""
    for r in ranked:
        if r.feasible:
            return r
    if not ranked:
        raise ValueError(
            f"no valid plan for {stats.model} on {mesh.name} "
            f"({mesh.num_devices} devices) at global batch "
            f"{global_batch}: every lattice point violates a hard "
            f"constraint (divisibility/capability)")
    near = min(ranked, key=lambda r: r.cost.peak_bytes)
    raise ValueError(
        f"no plan for {stats.model} on {mesh.name} fits the HBM budget "
        f"({near.cost.hbm_budget_bytes / 2**30:.2f} GiB/device): the "
        f"smallest predicted peak is {near.cost.peak_bytes / 2**30:.2f} "
        f"GiB ({near.plan.describe()}) — shrink the batch, grow the "
        f"mesh, or raise the budget")


def ranked_artifact(stats: ModelStats, mesh: MeshSpec, global_batch: int,
                    ranked: List[RankedPlan], top: int = 0) -> dict:
    """The ranked-plan JSON artifact (plan_main --out / bench_plan.py):
    workload + mesh + every (or top-N) ranked plan with its predicted
    cost, feasible plans first."""
    plans = ranked[:top] if top else ranked
    return {
        "model": stats.model,
        "family": stats.family,
        "seq_len": stats.seq_len,
        "params": stats.params,
        "global_batch": global_batch,
        "mesh": mesh.to_dict(),
        "feasible_count": sum(1 for r in ranked if r.feasible),
        "plan_count": len(ranked),
        "plans": [r.to_dict() for r in plans],
    }
