"""Ranked-lattice memoization: a JSON sidecar keyed by
(workload, mesh descriptor, batch) so repeated ``--plan auto`` resolves
skip the search.

The lattice for a big simulated pod is cheap but not free (hundreds of
plans × an analytic predict each), and plan resolution sits at the top
of EVERY planned run — launcher restarts included.  The cache stores
the full ranked artifact per key, so a hit reconstructs the exact
RankedPlan list the search would have produced (same objects the
ranking table, ``--out`` artifact, and ``--plan auto`` pick consume).

Key = sha1 over everything that determines the ranking: the cache
format version, the workload fingerprint (model name, family, seq_len,
EXACT param count — a registry edit that changes the model changes the
key), the full mesh descriptor dict, the global batch, the optimizer,
and the HBM fraction.  Anything else (a cost-model change) bumps
``CACHE_VERSION`` to invalidate wholesale.

Corrupt or unreadable sidecars degrade to a recompute with a warning —
a cache must never be able to fail a run.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
from typing import List, Optional, Tuple

from dtf_tpu.plan.cost_model import (DEFAULT_OVERLAP_FRAC, HBM_FRACTION,
                                     Plan, PlanCost)
from dtf_tpu.plan.mesh_spec import MeshSpec
from dtf_tpu.plan.model_stats import ModelStats
from dtf_tpu.plan.search import RankedPlan, search

log = logging.getLogger("dtf_tpu")

# bump when the ranking function changes (cost model, lattice, sort
# order) — stale entries must not resurrect an old ranking.
# v2: ZeRO stages 2/3 in the lattice + stage-aware wire-volume /
#     peak-bytes terms + the exposed-comm overlap term (overlap_frac
#     joins the key) — a v1 entry describes a DIFFERENT ranking
#     function and must recompute, not serve
# v3: measured-overlap calibration section — `plan_main --calibrate`
#     persists plan_overlap_frac_implied per (workload, mesh) and
#     auto-resolution (`--plan auto` with a cache, rankings without an
#     explicit --overlap_frac) reads it back, so the overlap fraction
#     an entry was ranked under may now be a measured number a v2 file
#     cannot carry — v2 entries recompute, not serve
CACHE_VERSION = 3


def cache_key(stats: ModelStats, mesh: MeshSpec, global_batch: int,
              optimizer: str, hbm_fraction: float = HBM_FRACTION,
              overlap_frac: float = DEFAULT_OVERLAP_FRAC
              ) -> Tuple[str, dict]:
    """(sha1 hex key, the human-readable payload stored beside it)."""
    payload = {
        "cache_version": CACHE_VERSION,
        "model": stats.model,
        "family": stats.family,
        "seq_len": stats.seq_len,
        "params": stats.params,
        "mesh": mesh.to_dict(),
        "global_batch": int(global_batch),
        "optimizer": optimizer,
        "hbm_fraction": hbm_fraction,
        "overlap_frac": overlap_frac,
    }
    blob = json.dumps(payload, sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest(), payload


def _ranked_from_dict(d: dict) -> RankedPlan:
    pred = dict(d["predicted"])
    pred.pop("feasible", None)            # a property, not a field
    return RankedPlan(plan=Plan.from_dict(d["plan"]),
                      cost=PlanCost(**pred),
                      violations=tuple(d.get("violations", ())))


def load_ranking(path: str, key: str) -> Optional[List[RankedPlan]]:
    """The cached ranking for ``key``, or None (miss / unreadable —
    unreadable warns and recomputes, it never raises)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        entry = doc.get("entries", {}).get(key)
        if entry is None:
            return None
        return [_ranked_from_dict(r) for r in entry["ranked"]]
    except (OSError, ValueError, KeyError, TypeError) as e:
        log.warning("plan cache %s unreadable (%s: %s) — recomputing",
                    path, type(e).__name__, e)
        return None


def _merge_into_doc(path: str, mutate) -> None:
    """Read-modify-write the sidecar atomically (tmp + rename — two
    racing writers at worst each write a complete file).  A
    version-mismatched or corrupt existing file is overwritten fresh.
    Write failures warn and continue: the result in hand is
    unaffected."""
    try:
        doc = {"cache_version": CACHE_VERSION, "entries": {}}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    existing = json.load(f)
                if existing.get("cache_version") == CACHE_VERSION:
                    doc = existing
            except (OSError, ValueError):
                pass                      # overwrite the corrupt file
        mutate(doc)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    except OSError as e:
        log.warning("plan cache %s not writable (%s) — result still "
                    "used, just not memoized", path, e)


def store_ranking(path: str, key: str, payload: dict,
                  ranked: List[RankedPlan]) -> None:
    """Merge one ranking entry into the sidecar."""
    def mutate(doc):
        doc.setdefault("entries", {})[key] = {
            "workload": payload,
            "ranked": [r.to_dict() for r in ranked],
        }
    _merge_into_doc(path, mutate)


# ---------------------------------------------------------------------------
# Measured-overlap calibration (the --calibrate feedback loop).  The
# cost model's ZeRO-2/3 exposed-comm term credits an overlap fraction;
# `plan_main --calibrate` MEASURES the implied fraction on a live box
# (plan_overlap_frac_implied).  Persisting it here, keyed by (workload,
# mesh) — NOT by batch or optimizer, which don't change how well the
# scheduler hides the wire — closes the loop without an operator:
# every later `--plan auto` resolve and ranking against the same cache
# uses the measured fraction instead of DEFAULT_OVERLAP_FRAC.
# ---------------------------------------------------------------------------

def calibration_key(stats: ModelStats, mesh: MeshSpec) -> Tuple[str, dict]:
    """(sha1 hex key, human-readable payload) for one calibration
    point."""
    payload = {
        "cache_version": CACHE_VERSION,
        "model": stats.model,
        "family": stats.family,
        "seq_len": stats.seq_len,
        "params": stats.params,
        "mesh": mesh.to_dict(),
    }
    blob = json.dumps(payload, sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest(), payload


def store_calibration(path: str, stats: ModelStats, mesh: MeshSpec,
                      overlap_frac_implied: float) -> None:
    """Persist a measured overlap fraction for (workload, mesh)."""
    key, payload = calibration_key(stats, mesh)
    def mutate(doc):
        doc.setdefault("calibrations", {})[key] = {
            "workload": payload,
            "overlap_frac_implied": float(overlap_frac_implied),
        }
    _merge_into_doc(path, mutate)
    log.info("plan cache: persisted measured overlap_frac %.2f for "
             "(%s, %s)", overlap_frac_implied, stats.model, mesh.name)


def load_calibration(path: str, stats: ModelStats,
                     mesh: MeshSpec) -> Optional[float]:
    """The persisted measured overlap fraction for (workload, mesh), or
    None (no calibration / unreadable / out-of-range — all degrade to
    the model default, never to an error)."""
    if not os.path.exists(path):
        return None
    key, _ = calibration_key(stats, mesh)
    try:
        with open(path) as f:
            doc = json.load(f)
        entry = doc.get("calibrations", {}).get(key)
        if entry is None:
            return None
        val = float(entry["overlap_frac_implied"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        log.warning("plan cache %s calibration unreadable (%s: %s) — "
                    "using the default overlap fraction", path,
                    type(e).__name__, e)
        return None
    return val if 0.0 <= val <= 1.0 else None


def cached_search(path: str, stats: ModelStats, mesh: MeshSpec,
                  global_batch: int, optimizer: str = "sgd",
                  overlap_frac: Optional[float] = None
                  ) -> Tuple[List[RankedPlan], bool]:
    """search() through the sidecar: (ranked, was_a_hit).

    ``overlap_frac=None`` means AUTO: use the persisted measured
    calibration for this (workload, mesh) when one exists — the
    ``--calibrate`` feedback loop closing without an operator — else
    ``DEFAULT_OVERLAP_FRAC``.  An explicit value always wins.  The
    fraction is part of the ranking key, so a fresh calibration never
    serves a stale ranking."""
    if overlap_frac is None:
        cal = load_calibration(path, stats, mesh)
        if cal is not None:
            log.info("plan cache: using calibrated overlap_frac %.2f "
                     "for (%s, %s)", cal, stats.model, mesh.name)
        overlap_frac = cal if cal is not None else DEFAULT_OVERLAP_FRAC
    key, payload = cache_key(stats, mesh, global_batch, optimizer,
                             overlap_frac=overlap_frac)
    cached = load_ranking(path, key)
    if cached is not None:
        log.info("plan cache hit (%s, %s, batch %d) — search skipped",
                 stats.model, mesh.name, global_batch)
        return cached, True
    ranked = search(stats, mesh, global_batch, optimizer=optimizer,
                    overlap_frac=overlap_frac)
    store_ranking(path, key, payload, ranked)
    return ranked, False
