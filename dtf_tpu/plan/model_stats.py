"""Model characterization for the planner — per-layer param counts,
forward FLOPs, and activation-byte estimates, derived from the existing
model configs (transformer + resnet families).

Everything is computed from the registry's construction parameters
(``models.registry._REGISTRY`` partial keywords + module class
defaults), so ``characterize("transformer_small")`` describes exactly
the model ``build_model("transformer_small")`` builds.  Param counts
are EXACT for the transformer and CIFAR-ResNet families (test-pinned
against ``jax.eval_shape`` of the real ``model.init``); FLOPs count
matmul/conv MACs × 2 (elementwise work is ignored — it is neither the
compute nor the memory term that decides a plan); activation bytes
approximate the saved-for-backward set per example (flash attention
saves no S×S score matrix, so attention contributes O(S·d), not O(S²)).

Per-layer fields the cost model consumes:
  params / state    — trainable / non-trainable (BN stats) element count
  flops             — forward FLOPs per example
  act_bytes         — saved activation bytes per example, no remat
  act_tp_bytes      — the portion of act_bytes that divides by the
                      tensor-parallel degree (ff/head-sharded
                      intermediates; the residual-stream tensors stay
                      replicated under Megatron TP)
  remat_act_bytes   — bytes still saved when the layer is remat'd
                      (the block input)
  tp / stage        — whether params shard over the 'model' axis under
                      tensor parallelism / belong to the pipeline-
                      stacked blocks
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class LayerStats:
    name: str
    params: int = 0
    state: int = 0            # non-trainable elements (BN running stats)
    flops: int = 0            # forward FLOPs per example
    act_bytes: int = 0        # saved activations per example (no remat)
    act_tp_bytes: int = 0     # portion of act_bytes dividing by TP ways
    remat_act_bytes: int = 0  # saved per example when remat'd
    tp: bool = False          # params shard over the 'model' axis
    stage: bool = False       # pipeline-stacked block (stage-shardable)


@dataclasses.dataclass(frozen=True)
class ModelStats:
    model: str
    family: str               # transformer | pipeline_transformer |
                              # moe_transformer | resnet | cifar_resnet
    layers: Tuple[LayerStats, ...]
    seq_len: int = 0          # 0 for vision
    num_layers: int = 0       # stacked-block count (pipeline divisor)
    num_heads: int = 0        # TP divisibility constraint
    d_ff: int = 0             # TP divisibility constraint
    d_model: int = 0
    dtype_bytes: int = 4

    # -- capability surface (mirrors what cli/runner.py accepts) -------
    @property
    def supports_tp(self) -> bool:
        return self.family == "transformer"

    @property
    def supports_seq(self) -> bool:
        return self.family == "transformer" and self.seq_len > 0

    @property
    def supports_pipeline(self) -> bool:
        return self.family == "pipeline_transformer"

    @property
    def supports_remat(self) -> bool:
        # runner.py: transformer families take --remat; of the vision
        # family only resnet50 has a remat policy
        return self.family in ("transformer", "pipeline_transformer",
                               "resnet")

    # -- totals --------------------------------------------------------
    @property
    def params(self) -> int:
        return sum(l.params for l in self.layers)

    @property
    def state(self) -> int:
        return sum(l.state for l in self.layers)

    @property
    def flops(self) -> int:
        return sum(l.flops for l in self.layers)

    @property
    def act_bytes(self) -> int:
        return sum(l.act_bytes for l in self.layers)


def _model_ctor_kwargs(name: str) -> dict:
    """Construction parameters of a registry entry: the partial's
    keywords over the module class's dataclass defaults."""
    from dtf_tpu.models import registry

    if name not in registry._REGISTRY:
        raise ValueError(f"unknown model {name!r}; have "
                         f"{sorted(registry._REGISTRY)}")
    ctor = registry._REGISTRY[name][0]
    kw = {}
    if isinstance(ctor, functools.partial):
        kw = dict(ctor.keywords)
        ctor = ctor.func
    for field in dataclasses.fields(ctor):
        if field.name not in kw and field.default is not dataclasses.MISSING:
            kw[field.name] = field.default
    return kw


def characterize(model_name: str, *, seq_len: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 dtype_bytes: int = 4) -> ModelStats:
    """Per-layer stats for a registry model at a run's shapes.

    ``seq_len`` is the RUN's sequence length (defaults to the LM
    dataset's 2048); ``num_classes`` the vocabulary / class count
    (defaults to the registry default); ``dtype_bytes`` the compute
    dtype width (2 for bf16) — param storage is always counted f32 by
    the cost model, this only scales activations."""
    from dtf_tpu.models import registry

    if model_name.startswith("moe_transformer"):
        raise ValueError(
            f"model {model_name!r}: the planner does not model routed-"
            f"expert capacity/all_to_all traffic — plan MoE runs by hand")
    if model_name == "trivial":
        raise ValueError("model 'trivial' is a smoke artifact; there is "
                         "nothing to plan")
    default_classes = (registry._REGISTRY[model_name][1]
                       if model_name in registry._REGISTRY else None)
    if model_name.startswith(("transformer", "pipeline_transformer")):
        vocab = num_classes or default_classes
        return _characterize_transformer(model_name, vocab,
                                         seq_len or 2048, dtype_bytes)
    if model_name == "resnet50":
        return _characterize_resnet50(num_classes or default_classes,
                                      dtype_bytes)
    if model_name.startswith("resnet"):
        return _characterize_cifar_resnet(model_name,
                                          num_classes or default_classes,
                                          dtype_bytes)
    raise ValueError(f"unknown model {model_name!r}")


# ---------------------------------------------------------------------------
# Transformer family (models/transformer.py, models/pipeline_lm.py)
# ---------------------------------------------------------------------------

def _characterize_transformer(name: str, vocab: int, seq: int,
                              dt: int) -> ModelStats:
    kw = _model_ctor_kwargs(name)
    L, d = kw["num_layers"], kw["d_model"]
    heads, ff = kw["num_heads"], kw["d_ff"]
    max_seq = kw.get("max_seq_len", 2048)
    family = ("pipeline_transformer" if name.startswith("pipeline")
              else "transformer")
    layers = [
        # embed V·d + learned pos table max_seq_len·d; act: the [S, d]
        # embedded stream
        LayerStats("embed", params=vocab * d + max_seq * d,
                   flops=0, act_bytes=seq * d * dt),
    ]
    # one block: ln1 2d | qkv d·3d+3d | out d·d (no bias) | ln2 2d |
    # fc1 d·ff+ff | fc2 ff·d (no bias)
    blk_params = (2 * d) + (3 * d * d + 3 * d) + (d * d) + (2 * d) \
        + (d * ff + ff) + (ff * d)
    # matmul MACs ×2; causal flash attention does S²/2·d score MACs and
    # the same again for the value aggregation → 2·S²·d FLOPs total
    blk_flops = 2 * seq * d * (4 * d + 2 * ff) + 2 * seq * seq * d
    # saved-for-backward per example: residual stream x, ln1, attn_out,
    # ln2 (replicated under TP) + qkv, pre-projection heads, fc1 out,
    # gelu out (these shard over the TP ways)
    act_rep = 4 * seq * d * dt
    act_tp = (4 * seq * d + 2 * seq * ff) * dt
    for i in range(L):
        layers.append(LayerStats(
            f"block{i}", params=blk_params, flops=blk_flops,
            act_bytes=act_rep + act_tp, act_tp_bytes=act_tp,
            remat_act_bytes=seq * d * dt, tp=True, stage=True))
    # ln_f 2d; lm_head d·V+V; the f32 logits [S, V] are the single
    # largest activation of a small-model step — counted here
    layers.append(LayerStats(
        "head", params=2 * d + d * vocab + vocab,
        flops=2 * seq * d * vocab,
        act_bytes=seq * d * dt + seq * vocab * 4,
        remat_act_bytes=seq * d * dt + seq * vocab * 4))
    return ModelStats(model=name, family=family, layers=tuple(layers),
                      seq_len=seq, num_layers=L, num_heads=heads,
                      d_ff=ff, d_model=d, dtype_bytes=dt)


# ---------------------------------------------------------------------------
# Vision families (models/resnet_cifar.py, models/resnet.py)
# ---------------------------------------------------------------------------

def _conv(name: str, k: int, cin: int, cout: int, hout: int, dt: int,
          with_bn: bool = True, **extra) -> LayerStats:
    """3×3/1×1 conv (+BN) layer: params k²·cin·cout (+2·cout BN params,
    2·cout running stats); FLOPs 2·k²·cin·cout·H·W at the OUTPUT
    resolution; saved activations ≈ conv output + post-BN/ReLU copy."""
    return LayerStats(
        name,
        params=k * k * cin * cout + (2 * cout if with_bn else 0),
        state=2 * cout if with_bn else 0,
        flops=2 * k * k * cin * cout * hout * hout,
        act_bytes=2 * hout * hout * cout * dt,
        remat_act_bytes=hout * hout * cout * dt, **extra)


def _characterize_cifar_resnet(name: str, classes: int, dt: int
                               ) -> ModelStats:
    n = _model_ctor_kwargs(name)["num_blocks"]
    layers = [_conv("conv1", 3, 3, 16, 32, dt)]
    specs = ((16, 16, 32), (16, 32, 16), (32, 64, 8))  # cin, cout, H
    for s, (cin, cout, h) in enumerate(specs, start=2):
        for b in range(n):
            cb = cout if b else cin
            block = [_conv(f"stage{s}_block{b}_conv_a", 3, cb, cout, h, dt),
                     _conv(f"stage{s}_block{b}_conv_b", 3, cout, cout, h,
                           dt)]
            if b == 0:  # projection shortcut (1×1 conv + BN)
                block.append(_conv(f"stage{s}_block{b}_proj", 1, cin,
                                   cout, h, dt))
            layers.extend(block)
    layers.append(LayerStats("fc", params=64 * classes + classes,
                             flops=2 * 64 * classes,
                             act_bytes=(64 + classes) * dt))
    return ModelStats(model=name, family="cifar_resnet",
                      layers=tuple(layers), num_layers=3 * n)


def _characterize_resnet50(classes: int, dt: int) -> ModelStats:
    layers = [_conv("conv1", 7, 3, 64, 112, dt)]
    h = 56  # after the 3×3/2 max-pool
    cin = 64
    for s, (f, blocks) in enumerate(((64, 3), (128, 4), (256, 6),
                                     (512, 3)), start=1):
        if s > 1:
            h //= 2  # the stage's stride-2 sits on block0's 3×3 conv
        for b in range(blocks):
            cb = cin if b == 0 else 4 * f
            pre = [_conv(f"stage{s}_block{b}_conv_a", 1, cb, f, h, dt),
                   _conv(f"stage{s}_block{b}_conv_b", 3, f, f, h, dt),
                   _conv(f"stage{s}_block{b}_conv_c", 1, f, 4 * f, h, dt)]
            if b == 0:
                pre.append(_conv(f"stage{s}_block{b}_proj", 1, cb, 4 * f,
                                 h, dt))
            layers.extend(pre)
        cin = 4 * f
    layers.append(LayerStats("fc", params=2048 * classes + classes,
                             flops=2 * 2048 * classes,
                             act_bytes=(2048 + classes) * dt))
    return ModelStats(model="resnet50", family="resnet",
                      layers=tuple(layers), num_layers=16)
