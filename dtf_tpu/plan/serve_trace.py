"""Serving-workload reconstruction — recorded traces and synthetic
arrival processes as one replayable shape.

The capacity simulator (plan/serve_model.py) replays an ARRIVAL
PROCESS through an analytic fleet model; this module produces that
process two ways:

  parse_workload / workload_from_records — reconstruct per-request
      records from the JSONL streams a traced serving run wrote
      (``trace_router*.jsonl`` + per-replica ``trace_rank{K}.jsonl``,
      or a bare single-engine run's stream): arrival time, prompt and
      generated token counts, prefix-share depth, queue wait, outcome
      (complete / shed / deadline), redispatch count.  Requests are
      keyed by their distributed-trace id, so a failover (requeue +
      second dispatch) folds into ONE record, and the router + replica
      views of the same request merge instead of double-counting.
      Records without a trace id cannot be joined and are counted
      (``skipped_no_trace``), never guessed at; torn JSONL tails are
      already dropped by the trace reader.

  synthetic_workload — deterministic arrival generators for
      extrapolation beyond recorded load: Poisson, square-wave BURST
      (rate × burst_factor for 1/burst_factor of each period — same
      mean rate, bursty arrivals), and shared-prefix mixes (a fraction
      of requests share one of G group prompts, the prefix-affinity /
      page-sharing traffic shape).

Field semantics the simulator relies on:

  arrival_s      — seconds relative to the workload window start.
  decode_tokens  — tokens the request generated (parsed completes) or
                   its budget (synthetic; greedy runs to budget unless
                   EOS, so budget is the honest planning number).
  prefix_group   — shared-prefix identity for registry modeling
                   (synthetic mixes).  Parsed traces cannot recover
                   group identity from records, so they carry the
                   MEASURED share depth instead:
  prefix_tokens  — shareable leading tokens.  With a group, the
                   simulator's registry model decides hits; without
                   one (parsed traces), the recorded hit is replayed
                   as-is.
  queue_wait_s / latency_s — measured values (calibration's ground
                   truth); synthetic records carry 0.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

from dtf_tpu.obs.registry import percentile

#: per-request record names the parser consumes (anything else in the
#: stream — batch spans, ledger events, health records — is ignored)
_ROUTER_KINDS = ("router_submit", "router_dispatch", "router_requeue",
                 "router_complete", "router_shed", "router_deadline")
_ENGINE_KINDS = ("serve_submit", "serve_admit", "serve_retire",
                 "serve_shed")


@dataclasses.dataclass
class RequestRecord:
    """One serving request, as the simulator replays it."""

    trace_id: str
    arrival_s: float
    prompt_tokens: int
    decode_tokens: int
    prefix_group: Optional[str] = None
    prefix_tokens: int = 0
    outcome: str = "complete"        # complete | shed | deadline | incomplete
    queue_wait_s: float = 0.0
    latency_s: float = 0.0
    redispatches: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Workload:
    """An arrival process plus its observation window."""

    requests: List[RequestRecord]
    duration_s: float
    source: str
    skipped_no_trace: int = 0

    @property
    def rate_rps(self) -> float:
        return len(self.requests) / self.duration_s \
            if self.duration_s > 0 else 0.0

    def summary(self) -> dict:
        return {
            "source": self.source,
            "requests": len(self.requests),
            "duration_s": round(self.duration_s, 3),
            "rate_rps": round(self.rate_rps, 3),
            "skipped_no_trace": self.skipped_no_trace,
        }


def parse_workload(paths: Sequence[str]) -> Workload:
    """Workload from trace dirs / files (``trace_main`` discovery
    rules: per-rank streams plus named router streams)."""
    from dtf_tpu.cli.trace_main import discover, merge_records
    merged = merge_records(discover(list(paths)))
    return workload_from_records(
        merged, source="trace:" + ",".join(str(p) for p in paths))


def workload_from_records(merged: List[dict],
                          source: str = "records") -> Workload:
    """Per-request reconstruction from a time-ordered merged record
    stream (``trace_main.merge_records`` output).

    Router lifecycle records own a request's identity when present
    (arrival/queue-wait/outcome from the tier front-end); the
    replica-side engine records fill what only the engine knows
    (prefix-share depth) and stand alone for router-less runs.  One
    record per trace id no matter how many failover attempts the
    stream recorded."""
    reqs: Dict[str, dict] = {}
    skipped = 0

    def entry(tid: str) -> dict:
        return reqs.setdefault(tid, {
            "arrival": None, "engine_arrival": None, "prompt": 0,
            "decode": 0, "outcome": "incomplete", "queue_wait": None,
            "engine_queue_wait": None, "latency": 0.0, "redispatches": 0,
            "prefix_tokens": 0, "has_router": False,
        })

    for rec in merged:
        name = rec.get("name")
        if name not in _ROUTER_KINDS and name not in _ENGINE_KINDS:
            continue
        tid = rec.get("trace")
        if not tid:
            # a per-request record that cannot be joined: counted, not
            # guessed (old traces, tracing enabled mid-run, ...)
            skipped += 1
            continue
        r = entry(str(tid))
        ts = float(rec.get("ts", 0.0))
        if name == "router_submit":
            r["has_router"] = True
            r["arrival"] = ts if r["arrival"] is None \
                else min(r["arrival"], ts)
            r["prompt"] = int(rec.get("prompt_len", r["prompt"]) or 0)
        elif name == "router_dispatch":
            r["has_router"] = True
            if r["queue_wait"] is None:
                # every dispatch record carries the latched
                # first-attempt wait (a failed attempt-1 send leaves
                # no attempt-1 record — the attempt-2 record still
                # has the right number); ts − arrival is the
                # older-trace fallback, valid only for attempt 1
                if rec.get("queue_wait_s") is not None:
                    r["queue_wait"] = float(rec["queue_wait_s"])
                elif (int(rec.get("attempt", 1)) == 1
                      and r["arrival"] is not None):
                    r["queue_wait"] = max(0.0, ts - r["arrival"])
        elif name == "router_requeue":
            r["has_router"] = True
            r["redispatches"] = max(r["redispatches"],
                                    int(rec.get("redispatches", 0) or 0))
        elif name == "router_complete":
            r["has_router"] = True
            r["outcome"] = "complete"
            r["decode"] = int(rec.get("tokens", 0) or 0)
            r["latency"] = float(rec.get("latency_s", 0.0) or 0.0)
        elif name == "router_shed":
            # admission sheds never reach router_submit — the anomaly
            # IS the arrival record
            r["has_router"] = True
            r["outcome"] = "shed"
            if r["arrival"] is None:
                r["arrival"] = ts
        elif name == "router_deadline":
            r["has_router"] = True
            r["outcome"] = "deadline"
            # the tokens it streamed before failing are real demand —
            # a replay that floors them to 1 under-loads the fleet
            r["decode"] = max(r["decode"],
                              int(rec.get("delivered", 0) or 0))
        elif name == "serve_submit":
            r["engine_arrival"] = ts if r["engine_arrival"] is None \
                else min(r["engine_arrival"], ts)
            if not r["prompt"]:
                r["prompt"] = int(rec.get("prompt_len", 0) or 0)
        elif name == "serve_admit":
            if rec.get("queue_wait_s") is not None:
                r["engine_queue_wait"] = float(rec["queue_wait_s"])
            if rec.get("shared_tokens"):
                # a failover's second admission may hit deeper (the
                # first attempt registered the prefix) — keep the max
                r["prefix_tokens"] = max(r["prefix_tokens"],
                                         int(rec["shared_tokens"]))
        elif name == "serve_retire":
            if not r["has_router"]:
                r["outcome"] = "complete"
                r["decode"] = int(rec.get("tokens", 0) or 0)
                r["latency"] = float(rec.get("latency_s", 0.0) or 0.0)
        elif name == "serve_shed":
            if not r["has_router"]:
                r["outcome"] = "shed"
                if r["engine_arrival"] is None:
                    r["engine_arrival"] = ts

    # resolve: router fields win where both tiers saw the request
    resolved = []
    t_end = 0.0
    for tid, r in reqs.items():
        arrival = r["arrival"] if r["arrival"] is not None \
            else r["engine_arrival"]
        if arrival is None:
            skipped += 1    # e.g. only a serve_admit survived a crash
            continue
        wait = r["queue_wait"] if r["has_router"] \
            and r["queue_wait"] is not None else r["engine_queue_wait"]
        resolved.append((arrival, RequestRecord(
            trace_id=tid, arrival_s=arrival,
            prompt_tokens=r["prompt"], decode_tokens=r["decode"],
            prefix_tokens=r["prefix_tokens"], outcome=r["outcome"],
            queue_wait_s=float(wait or 0.0), latency_s=r["latency"],
            redispatches=r["redispatches"])))
        t_end = max(t_end, arrival + r["latency"])
    resolved.sort(key=lambda ar: (ar[0], ar[1].trace_id))
    if not resolved:
        return Workload([], 0.0, source, skipped_no_trace=skipped)
    t0 = resolved[0][0]
    requests = []
    for arrival, req in resolved:
        req.arrival_s = arrival - t0
        requests.append(req)
    return Workload(requests, max(t_end - t0, 1e-9), source,
                    skipped_no_trace=skipped)


def measured_stats(workload: Workload) -> dict:
    """Ground-truth aggregates of a PARSED workload — what the
    simulator's prediction is calibrated against.  Throughput spans
    first arrival → last completion (the same window the prediction
    reports); percentiles cover completed requests only, sheds and
    deadline failures are rates."""
    completes = [r for r in workload.requests if r.outcome == "complete"]
    sheds = sum(1 for r in workload.requests if r.outcome == "shed")
    deadlined = sum(1 for r in workload.requests
                    if r.outcome == "deadline")
    total = len(workload.requests)
    out = {
        "requests": total, "completed": len(completes), "shed": sheds,
        "deadlined": deadlined,
        "shed_rate": sheds / total if total else 0.0,
        "deadline_rate": deadlined / total if total else 0.0,
        "tokens_per_s": 0.0, "latency_p50_s": 0.0, "latency_p99_s": 0.0,
        "queue_wait_p50_s": 0.0, "queue_wait_p99_s": 0.0,
    }
    if not completes:
        return out
    span = (max(r.arrival_s + r.latency_s for r in completes)
            - min(r.arrival_s for r in completes))
    tokens = sum(r.decode_tokens for r in completes)
    lat = sorted(r.latency_s for r in completes)
    wait = sorted(r.queue_wait_s for r in completes)
    out.update(
        tokens_per_s=tokens / span if span > 0 else 0.0,
        latency_p50_s=percentile(lat, 50.0),
        latency_p99_s=percentile(lat, 99.0),
        queue_wait_p50_s=percentile(wait, 50.0),
        queue_wait_p99_s=percentile(wait, 99.0))
    return out


# ---------------------------------------------------------------------------
# synthetic arrival generation
# ---------------------------------------------------------------------------

ARRIVAL_PROCESSES = ("poisson", "burst")


def synthetic_workload(*, rate_rps: float, duration_s: float,
                       seed: int = 0, process: str = "poisson",
                       burst_factor: float = 4.0,
                       burst_period_s: Optional[float] = None,
                       prompt_tokens=(8, 64), decode_tokens: int = 32,
                       shared_fraction: float = 0.0,
                       shared_groups: int = 2,
                       shared_prefix_tokens: int = 128) -> Workload:
    """Deterministic synthetic arrival process.

    ``poisson`` draws exponential inter-arrival gaps at ``rate_rps``.
    ``burst`` is the square-wave-modulated variant: each
    ``burst_period_s`` window (default duration/8) opens with arrivals
    at ``rate_rps × burst_factor`` for 1/burst_factor of the period and
    stays silent for the rest — the MEAN rate is unchanged, the peaks
    are what capacity must absorb.

    ``shared_fraction`` of requests carry one of ``shared_groups``
    group prompts: ``shared_prefix_tokens`` shareable leading tokens
    plus a per-request tail drawn from ``prompt_tokens``; the rest
    draw their whole prompt from ``prompt_tokens``.
    """
    import numpy as np

    if process not in ARRIVAL_PROCESSES:
        raise ValueError(f"unknown arrival process {process!r}; have "
                         f"{ARRIVAL_PROCESSES}")
    if rate_rps <= 0 or duration_s <= 0:
        raise ValueError("rate_rps and duration_s must be positive")
    if not 0.0 <= shared_fraction <= 1.0:
        raise ValueError(f"shared_fraction must be in [0, 1], got "
                         f"{shared_fraction}")
    rng = np.random.default_rng(seed)
    lo, hi = int(prompt_tokens[0]), int(prompt_tokens[1])
    if lo < 1 or hi < lo:
        raise ValueError(f"prompt_tokens range ({lo}, {hi}) must be "
                         f"1 <= lo <= hi")
    period = float(burst_period_s or duration_s / 8.0)
    duty = 1.0 / max(burst_factor, 1.0)

    arrivals: List[float] = []
    t = 0.0
    while True:
        if process == "poisson":
            t += float(rng.exponential(1.0 / rate_rps))
        else:
            # burst: arrivals only inside the leading duty-window of
            # each period, at burst_factor × the mean rate
            t += float(rng.exponential(1.0 / (rate_rps * burst_factor)))
            phase = math.fmod(t, period)
            if phase > period * duty:
                # silent stretch: jump to the next period's window
                # start and REDRAW the gap from there (emitting at the
                # boundary itself would put a deterministic arrival at
                # every period start)
                t += period - phase
                if t >= duration_s:
                    break
                continue
        if t >= duration_s:
            break
        arrivals.append(t)

    requests: List[RequestRecord] = []
    for i, arr in enumerate(arrivals):
        group = None
        prefix = 0
        plen = int(rng.integers(lo, hi + 1))
        if shared_fraction > 0 and rng.random() < shared_fraction:
            group = f"g{int(rng.integers(shared_groups))}"
            prefix = int(shared_prefix_tokens)
            plen += prefix
        requests.append(RequestRecord(
            trace_id=f"syn{i:06d}", arrival_s=arr, prompt_tokens=plen,
            decode_tokens=int(decode_tokens), prefix_group=group,
            prefix_tokens=prefix))
    desc = (f"synthetic:{process},rate={rate_rps:g},dur={duration_s:g},"
            f"seed={seed}"
            + (f",shared={shared_fraction:g}/{shared_groups}"
               if shared_fraction else ""))
    return Workload(requests, float(duration_s), desc)


def scale_workload(workload: Workload, target_rps: float) -> Workload:
    """Time-compress/stretch a workload to a target mean arrival rate
    (request mix, ordering, and relative burstiness preserved — the
    honest way to ask 'this traffic shape at X req/s')."""
    if target_rps <= 0:
        raise ValueError(f"target_rps must be positive, got {target_rps}")
    cur = workload.rate_rps
    if not workload.requests or cur <= 0:
        return workload
    factor = cur / target_rps
    requests = [dataclasses.replace(r, arrival_s=r.arrival_s * factor)
                for r in workload.requests]
    return Workload(requests, workload.duration_s * factor,
                    f"{workload.source}→{target_rps:g}rps",
                    skipped_no_trace=workload.skipped_no_trace)
