"""Mesh descriptor for the parallelism planner.

A :class:`MeshSpec` is everything the analytic cost model needs to know
about the hardware — device count and layout (hosts × devices/host),
HBM bytes per device, achievable dense-matmul FLOP/s, and the two
collective-bandwidth tiers (intra-host ICI vs cross-host DCN).  It is
*simulatable*: a plan for a 4-host × 4-chip pod can be ranked on this
CPU box, because nothing here requires the described hardware to be
attached.

Numbers in the presets are order-of-magnitude engineering estimates
(achievable, not datasheet peak — e.g. the v4 entry uses ~50% of the
275 TFLOP/s bf16 peak, the sustained fraction a well-tiled matmul
reaches), good enough to *rank* plans; ``calibrate_device_flops`` runs
a short measured matmul probe for the calibration loop that compares
predicted vs measured step time on live hardware.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

KiB, MiB, GiB = 1024, 1024 ** 2, 1024 ** 3


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Hardware description consumed by the cost model."""

    name: str
    num_hosts: int
    devices_per_host: int
    hbm_bytes: int        # per-device HBM (host RAM share for CPU)
    device_flops: float   # achievable dense FLOP/s per device
    intra_bw: float       # bytes/s per device for intra-host collectives
    inter_bw: float       # bytes/s per device once a ring crosses hosts

    def __post_init__(self):
        if self.num_hosts < 1 or self.devices_per_host < 1:
            raise ValueError(f"mesh {self.name!r}: needs >= 1 host and "
                             f">= 1 device per host")
        if min(self.hbm_bytes, self.device_flops, self.intra_bw,
               self.inter_bw) <= 0:
            raise ValueError(f"mesh {self.name!r}: hbm/flops/bandwidth "
                             f"must all be positive")

    @property
    def num_devices(self) -> int:
        return self.num_hosts * self.devices_per_host

    def axis_bandwidth(self, stride: int, size: int) -> float:
        """Per-device collective bandwidth for a mesh axis whose ring
        neighbors are ``stride`` devices apart (the runtime lays the
        ('data','seq','model') mesh out row-major over the host-major
        device list, so an axis's span is stride × size): a ring whose
        whole span fits in one host runs at ICI speed, anything wider
        is gated by the cross-host link."""
        if size <= 1:
            return self.intra_bw
        return (self.intra_bw if stride * size <= self.devices_per_host
                else self.inter_bw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# Presets.  "cpu" is sized for the 8-virtual-device test mesh on a dev
# box (flops deliberately conservative — the calibration probe replaces
# it with a measurement); the TPU entries model one v4 host and the
# docs' worked 4-host × 4-device pod.
PRESETS: Dict[str, MeshSpec] = {
    "cpu": MeshSpec("cpu", num_hosts=1, devices_per_host=8,
                    hbm_bytes=4 * GiB, device_flops=8e9,
                    intra_bw=8e9, inter_bw=1e9),
    # one v4 host, 4 chips: 32 GiB HBM/chip, ~50% of 275 TFLOP/s bf16
    # peak achievable, ICI ~1e11 B/s effective allreduce bandwidth
    "v4-8": MeshSpec("v4-8", num_hosts=1, devices_per_host=4,
                     hbm_bytes=32 * GiB, device_flops=1.4e14,
                     intra_bw=1e11, inter_bw=2.5e10),
    # the README/DESIGN worked example: 4 hosts × 4 chips over DCN
    "4x4": MeshSpec("4x4", num_hosts=4, devices_per_host=4,
                    hbm_bytes=32 * GiB, device_flops=1.4e14,
                    intra_bw=1e11, inter_bw=2.5e10),
}

_SUFFIX = {"k": 1e3, "m": 1e6, "g": 1e9, "t": 1e12, "p": 1e15}
# byte quantities use binary multipliers, so the documented descriptor
# "hbm=32g" means exactly the presets' 32 GiB — not 32e9 B, a 7%
# discrepancy that would flip feasibility between the two spellings
_BYTE_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def _num(text: str, *, binary: bool = False) -> float:
    text = text.strip().lower()
    table = _BYTE_SUFFIX if binary else _SUFFIX
    if text and text[-1] in table:
        return float(text[:-1]) * table[text[-1]]
    return float(text)


def mesh_spec(spec: str = "", *, live_devices: Optional[int] = None
              ) -> MeshSpec:
    """Resolve a ``--plan_mesh`` value.

    "" (default)    — describe the live runtime: CPU preset resized to
                      the actual jax topology (process count × local
                      devices), so plans search the mesh a run would
                      actually get.
    preset name     — one of PRESETS (``cpu``, ``v4-8``, ``4x4``).
    "k=v,…" string  — explicit descriptor, e.g.
                      ``hosts=4,devices=4,hbm=32g,flops=140t,intra=100g,inter=25g``
                      (numbers take k/m/g/t suffixes — binary for hbm
                      so ``32g`` ≡ 32 GiB like the presets, decimal for
                      the rates).  Unset keys inherit from the ``cpu``
                      preset.

    ``live_devices`` bounds the LIVE path's devices per host (an
    explicit ``--num_devices``); presets/descriptors ignore it.
    """
    if not spec:
        from dtf_tpu.runtime.mesh import topology
        topo = topology()
        # the live platform picks the per-device numbers: a TPU box
        # gets the v4 preset's HBM/FLOPs/ICI — keeping the cpu
        # preset's 4 GiB on a real 32 GiB chip would reject plans
        # that comfortably fit
        base = PRESETS["v4-8" if topo["platform"] == "tpu" else "cpu"]
        local = (live_devices if live_devices is not None
                 else topo["devices_per_host"])
        return dataclasses.replace(base, name="runtime",
                                   num_hosts=topo["num_hosts"],
                                   devices_per_host=local)
    if spec in PRESETS:
        return PRESETS[spec]
    if "=" not in spec:
        raise ValueError(
            f"unknown mesh preset {spec!r}; have {sorted(PRESETS)} or a "
            f"'hosts=4,devices=4,hbm=32g,flops=140t,intra=100g,inter=25g' "
            f"descriptor")
    base = PRESETS["cpu"]
    kw = dict(name=spec, num_hosts=base.num_hosts,
              devices_per_host=base.devices_per_host,
              hbm_bytes=base.hbm_bytes, device_flops=base.device_flops,
              intra_bw=base.intra_bw, inter_bw=base.inter_bw)
    keys = {"hosts": "num_hosts", "devices": "devices_per_host",
            "hbm": "hbm_bytes", "flops": "device_flops",
            "intra": "intra_bw", "inter": "inter_bw"}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        k = k.strip().lower()
        if k not in keys:
            raise ValueError(f"unknown mesh descriptor key {k!r}; have "
                             f"{sorted(keys)}")
        val = _num(v, binary=(k == "hbm"))
        kw[keys[k]] = int(val) if keys[k] in ("num_hosts",
                                              "devices_per_host",
                                              "hbm_bytes") else val
    return MeshSpec(**kw)


def calibrate_device_flops(repeats: int = 3) -> float:
    """Measured achievable FLOP/s for TRAINING-STEP-SHAPED work on one
    live device.

    A bare GEMM probe overestimates what a real step sustains by 5-50×
    on CPU (measured on this box: 1e12 FLOP/s for a 1024³ matmul chain
    vs ~3e10 achieved by an actual fwd+bwd — small per-op shapes,
    softmax/layernorm/optimizer traffic, dispatch overhead).  So the
    probe is a jitted forward+backward of the registry's
    ``transformer_small`` at a tiny batch, divided by its ANALYTIC flop
    count (the same accounting the cost model uses) — the resulting
    rate carries exactly the inefficiencies a predicted step will hit,
    which is what makes predicted-vs-measured land within the 2×
    calibration contract."""
    import jax
    import jax.numpy as jnp
    import optax

    from dtf_tpu.models import build_model
    from dtf_tpu.plan.model_stats import characterize

    batch, seq = 2, 64
    model, _ = build_model("transformer_small", dtype=jnp.float32)
    stats = characterize("transformer_small", seq_len=seq)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    params = jax.jit(model.init, static_argnames=("train",))(
        jax.random.key(0), tokens, train=False)["params"]

    def loss(p):
        logits, _ = model.apply({"params": p}, tokens, train=True,
                                mutable=["aux_loss"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens).mean()

    step = jax.jit(jax.grad(loss))
    jax.block_until_ready(step(params))  # compile + warm caches
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = step(params)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    # fwd + backward ≈ 3× forward MACs — the cost model's convention
    return repeats * 3.0 * stats.flops * batch / max(dt, 1e-9)
