"""Process + device initialization and mesh construction.

TPU-native successor of the reference's L1/L2 layers (SURVEY.md §1):
``TF_CONFIG`` parsing + grpc server rendezvous + strategy objects
(reference distribution_utils call sites, resnet_cifar_main.py:100-105)
become: ``jax.distributed.initialize`` for multi-host rendezvous over
DCN, and a ``jax.sharding.Mesh`` whose axes carry the parallelism:

    ('data', 'seq', 'model')

The reference is data-parallel only (SURVEY §2.2) so 'seq' and 'model'
default to size 1, but the mesh keeps them open — adding tensor or
sequence (ring-attention) parallelism is a config change, not a
redesign.

Rank-concept mapping (SURVEY §5.8):
    hvd.rank()        → jax.process_index()
    hvd.local_rank()  → local device ordinal
    hvd.size()        → jax.process_count() / device_count()
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np

# Some TPU platform plugins register themselves even when JAX_PLATFORMS
# asks for cpu; honor the user's env var explicitly (needed for the
# virtual-device CPU-mesh workflow on a machine with a TPU attached).
if os.environ.get("JAX_PLATFORMS"):
    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # backend already initialized — leave it be
        pass
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu.config import Config

log = logging.getLogger("dtf_tpu")

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
MESH_AXES = (DATA_AXIS, SEQ_AXIS, MODEL_AXIS)

_distributed_initialized = False


def _maybe_init_distributed(cfg: Config) -> None:
    """Multi-host rendezvous — the grpc-server/Distribute-Coordinator
    equivalent (evidence in reference ps_server/log0.log)."""
    global _distributed_initialized
    if _distributed_initialized:
        return
    if cfg.process_count and cfg.process_count > 1:
        if not cfg.coordinator_address or cfg.process_id is None:
            raise ValueError(
                "multi-process run needs coordinator_address and process_id "
                "(set flags, DTF_* env vars, or TF_CONFIG)")
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.process_count,
            process_id=cfg.process_id,
        )
        _distributed_initialized = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def is_coordinator() -> bool:
    """The hvd-rank-0 predicate used to gate checkpoints/verbosity
    (reference resnet_imagenet_main_horovod.py:255-260)."""
    return jax.process_index() == 0


@dataclasses.dataclass
class MeshRuntime:
    """A constructed device mesh plus the sharding helpers the train
    loop needs.  This is the strategy-scope equivalent: variables are
    replicated, the batch is sharded over 'data' (× 'seq' for long
    sequences)."""

    mesh: Mesh
    strategy: str
    # Token datasets shard dim 1 (sequence) over the 'seq' axis as well;
    # set by the runner from DatasetSpec.is_sequence.  Harmless when the
    # seq axis has size 1.
    shard_seq: bool = False

    @property
    def num_replicas(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    # -- shardings -----------------------------------------------------
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def data_sharding(self, ndim: int = 1) -> NamedSharding:
        """Batch dim sharded over 'data'; for sequence data dim 1 is
        additionally sharded over 'seq'; rest replicated."""
        return NamedSharding(self.mesh, self.batch_spec(ndim))

    def batch_spec(self, ndim: int = 1) -> P:
        if self.shard_seq and ndim >= 2:
            return P(DATA_AXIS, SEQ_AXIS, *([None] * (ndim - 2)))
        return P(DATA_AXIS, *([None] * (ndim - 1)))

    def shard_batch(self, batch):
        """Place a host-global batch onto the mesh, sharded on dim 0.

        Accepts numpy or jax arrays (a pytree); in multi-process runs the
        per-host array is the local shard and we assemble a global array
        via make_array_from_process_local_data.
        """
        def put(x):
            x = np.asarray(x)
            sh = self.data_sharding(x.ndim)
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)
        return jax.tree_util.tree_map(put, batch)


def initialize(cfg: Config) -> MeshRuntime:
    """Build the runtime for a named distribution strategy.

    Strategy → mesh mapping (SURVEY §2.2 table, right column):
      off/one_device         — 1 device, mesh (1,1,1): plain jit
      mirrored               — all local devices on the data axis
      tpu                    — alias of mirrored over every addressable chip
      multi_worker_mirrored  — global mesh across processes (ICI within a
                               slice, DCN across), sync allreduce
      horovod                — same SPMD path; horovod-parity semantics
                               (broadcast-init ≡ seed-synced replicated init,
                               metric averaging ≡ pmean, rank-0 I/O)
      parameter_server       — SPMD reinterpretation (BASELINE.json north
                               star): synchronous data parallelism; the
                               async push/pull semantics of the reference
                               (ps_server/, SURVEY §3.4) do not map to the
                               TPU execution model and are provided as a
                               separate opt-in host-side mode (parallel/ps).
    """
    _maybe_init_distributed(cfg)
    strategy = cfg.distribution_strategy
    devices = jax.devices()

    if strategy in ("off", "one_device"):
        devices = devices[:1]
    elif cfg.num_devices:
        if strategy in ("mirrored",):
            devices = jax.local_devices()[: cfg.num_devices]
        else:
            devices = devices[: cfg.num_devices]
    elif strategy == "mirrored":
        devices = jax.local_devices()

    n = len(devices)
    mp, sp = cfg.model_parallelism, cfg.seq_parallelism
    if n % (mp * sp):
        raise ValueError(
            f"{n} devices not divisible by model_parallelism*seq_parallelism={mp * sp}")
    dp = n // (mp * sp)
    dev_array = np.array(devices).reshape(dp, sp, mp)
    mesh = Mesh(dev_array, MESH_AXES)
    log.info(
        "mesh initialized: strategy=%s devices=%d data=%d seq=%d model=%d "
        "process=%d/%d", strategy, n, dp, sp, mp,
        jax.process_index(), jax.process_count())
    return MeshRuntime(mesh=mesh, strategy=strategy)


def topology() -> dict:
    """The live process/device topology as the parallelism planner's
    mesh descriptor sees it: hosts (= processes), local devices per
    host, and the backend platform.  Read-only, but the device query
    initializes the jax backend — in a multi-process run call
    :func:`_maybe_init_distributed` first (runner._run does), or
    ``process_count()`` reports 1 and the later distributed
    rendezvous refuses an already-initialized backend."""
    return {
        "num_hosts": jax.process_count(),
        "devices_per_host": jax.local_device_count(),
        "platform": jax.devices()[0].platform,
    }


def make_mesh(devices: Optional[Sequence] = None, data: int = -1,
              seq: int = 1, model: int = 1) -> Mesh:
    """Direct mesh constructor for tests and advanced use."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        data = n // (seq * model)
    arr = np.array(devices[: data * seq * model]).reshape(data, seq, model)
    return Mesh(arr, MESH_AXES)
