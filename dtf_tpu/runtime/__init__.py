from dtf_tpu.runtime.mesh import (  # noqa: F401
    MeshRuntime,
    initialize,
    is_coordinator,
    local_device_count,
    process_count,
    process_index,
)
