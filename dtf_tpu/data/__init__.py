from dtf_tpu.data.base import DatasetSpec, get_dataset_spec  # noqa: F401
from dtf_tpu.data.synthetic import synthetic_input_fn  # noqa: F401
from dtf_tpu.data.pipeline import DevicePrefetcher, shard_for_process  # noqa: F401
