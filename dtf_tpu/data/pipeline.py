"""Host→device pipeline: sharding + double-buffered prefetch.

TPU-native equivalent of the reference's final `dataset.prefetch` +
MultiDeviceIterator host→device overlap (SURVEY §2.4 last row; the
reference even monkey-patched sleep-slack into prefetch,
common.py:380-403).  A background thread keeps `buffer_size` batches
already transferred and laid out on the mesh while the device computes.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

from dtf_tpu.runtime.mesh import MeshRuntime


def shard_for_process(items, process_id: int, process_count: int):
    """Disjoint 1/N split by position — the reference's shard-by-file
    rule (cifar_preprocessing.py:147-152)."""
    return items[process_id::process_count]


def all_processes_max(value: int) -> int:
    """Max of a host-local int across every process (identity when
    single-process).  Lets sharded eval pipelines with uneven per-host
    record counts agree on one collective batch count."""
    import jax
    if jax.process_count() == 1:
        return int(value)
    import numpy as np
    from jax.experimental import multihost_utils
    vals = multihost_utils.process_allgather(np.asarray(value, np.int64))
    return int(np.max(vals))


class DevicePrefetcher:
    """Wraps a host batch iterator; yields mesh-sharded device arrays."""

    def __init__(self, it: Iterator, runtime: MeshRuntime, buffer_size: int = 2):
        self._it = it
        self._rt = runtime
        self._q: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._err = None
        self._done = False
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        try:
            for batch in self._it:
                self._q.put(self._rt.shard_batch(batch))
        except Exception as e:  # surfaced on next()
            self._err = e
        finally:
            self._q.put(None)

    def __iter__(self):
        return self

    def __next__(self):
        # the worker's terminal None is put exactly once — latch it, so
        # a SECOND __next__ after the error (a retry loop, a tqdm
        # wrapper, a confused caller) re-raises instead of blocking
        # forever on the now-empty queue
        if not self._done:
            item = self._q.get()
            if item is not None:
                return item
            self._done = True
        if self._err is not None:
            raise self._err
        raise StopIteration
