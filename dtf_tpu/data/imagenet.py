"""ImageNet TFRecord input pipeline.

Parity with reference imagenet_preprocessing.py:
  - shards train-%05d-of-01024 / validation-%05d-of-00128 (:144-153)
  - Example proto fields image/encoded, image/class/label (shifted to
    [0,1000), :254-255), image/object/bbox/{ymin,xmin,ymax,xmax}
    (:156-223)
  - train: sample a distorted bounding box (min_object_covered 0.1,
    aspect ∈ [0.75, 1.33], area ∈ [0.05, 1.0], 100 attempts, whole
    image on failure — :345-361), crop, random flip, bilinear resize to
    224×224 (:362-372, :483-500)
  - eval: aspect-preserving resize to shorter-side 256 then central
    224×224 crop (:375-394, :464-480)
  - both: channel-mean subtraction (123.68, 116.78, 103.94) without
    scaling (:397-430)
  - file-level shard per process, shuffle files each epoch, interleaved
    reads, shuffle buffer 10k, multi-threaded map
    (process_record_dataset :65-141)

JPEG decode uses the native C++ library (dtf_tpu/native, libjpeg) when
built, else PIL.  Decode+augment runs on a thread pool (the
`datasets_num_private_threads` equivalent) feeding a bounded queue.
"""

from __future__ import annotations

import io
import os
import queue
import threading
from typing import Iterator, Optional

import numpy as np

from dtf_tpu.data import records
from dtf_tpu.data.pipeline import shard_for_process

DEFAULT_IMAGE_SIZE = 224
NUM_CHANNELS = 3
NUM_TRAIN_FILES = 1024
NUM_VAL_FILES = 128
SHUFFLE_BUFFER = 10_000
CHANNEL_MEANS = np.array([123.68, 116.78, 103.94], np.float32)  # R, G, B
RESIZE_MIN = 256


def get_filenames(is_training: bool, data_dir: str):
    if is_training:
        names = [os.path.join(data_dir, f"train-{i:05d}-of-01024")
                 for i in range(NUM_TRAIN_FILES)]
    else:
        names = [os.path.join(data_dir, f"validation-{i:05d}-of-00128")
                 for i in range(NUM_VAL_FILES)]
    present = [n for n in names if os.path.exists(n)]
    if not present:
        raise FileNotFoundError(
            f"no ImageNet TFRecord shards found under {data_dir}")
    return present


def _load_native_jpeg():
    try:
        from PIL import Image
        from dtf_tpu.native import jpeg as native_jpeg
        probe = io.BytesIO()
        Image.new("RGB", (2, 2)).save(probe, format="JPEG")
        if native_jpeg.shape(probe.getvalue()) != (2, 2):
            return None
        return native_jpeg
    except Exception:
        return None

_native_jpeg = None
_native_probed = False


def native_jpeg_module():
    global _native_jpeg, _native_probed
    if not _native_probed:
        _native_jpeg = _load_native_jpeg()
        _native_probed = True
    return _native_jpeg


def decode_jpeg(buf: bytes) -> np.ndarray:
    """RGB uint8 HWC decode; native lib if built, else PIL."""
    nj = native_jpeg_module()
    if nj is not None:
        try:
            return nj.decode(buf)
        except ValueError:
            pass  # e.g. progressive/CMYK edge cases → PIL
    from PIL import Image
    img = Image.open(io.BytesIO(buf))
    if img.mode != "RGB":
        img = img.convert("RGB")
    return np.asarray(img, dtype=np.uint8)


def _resize_bilinear(image: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize (half-pixel centers, like tf.image.resize v2)."""
    from PIL import Image
    return np.asarray(
        Image.fromarray(image).resize((out_w, out_h), Image.BILINEAR),
        dtype=np.float32)


def _round_u8(images: np.ndarray) -> np.ndarray:
    """Round-half-up to the uint8 wire — the native StoreU8 policy
    (floor(v + 0.5)); bilinear samples of uint8 sources stay in
    [0, 255], the clip only guards fp drift."""
    return np.clip(np.floor(images + 0.5), 0, 255).astype(np.uint8)


def _meansub_to_u8(images: np.ndarray, ok: np.ndarray) -> np.ndarray:
    """Reconstruct the uint8 wire from a mean-subtracted f32 batch
    (stale-.so fallback: the native op only produced the f32 wire).
    Only rows with ok=True are converted — failed rows of the np.empty
    output hold uninitialized memory (possible NaN → numpy cast
    warnings) and are patched by the caller's re-decode anyway."""
    out = np.zeros(images.shape, np.uint8)
    out[ok] = _round_u8(images[ok] + CHANNEL_MEANS)
    return out


def sample_distorted_bbox(rng: np.random.Generator, height: int, width: int,
                          bbox: Optional[np.ndarray],
                          min_object_covered: float = 0.1,
                          aspect_ratio_range=(0.75, 1.33),
                          area_range=(0.05, 1.0),
                          max_attempts: int = 100):
    """Numpy re-derivation of tf.image.sample_distorted_bounding_box
    with the reference's constants (:354-361).  Returns (y, x, h, w);
    whole image when no attempt satisfies the constraints."""
    if bbox is None or len(bbox) == 0:
        bbox = np.array([[0.0, 0.0, 1.0, 1.0]], np.float32)
    for _ in range(max_attempts):
        aspect = rng.uniform(*aspect_ratio_range)
        area_frac = rng.uniform(*area_range)
        target_area = area_frac * height * width
        w = int(round(np.sqrt(target_area * aspect)))
        h = int(round(np.sqrt(target_area / aspect)))
        if w > width or h > height or h <= 0 or w <= 0:
            continue
        y = rng.integers(0, height - h + 1)
        x = rng.integers(0, width - w + 1)
        # object coverage: fraction of a ground-truth box inside the crop
        by0, bx0, by1, bx1 = bbox[0] * [height, width, height, width]
        inter_h = max(0.0, min(y + h, by1) - max(y, by0))
        inter_w = max(0.0, min(x + w, bx1) - max(x, bx0))
        box_area = max((by1 - by0) * (bx1 - bx0), 1e-6)
        if inter_h * inter_w / box_area >= min_object_covered:
            return int(y), int(x), int(h), int(w)
    return 0, 0, height, width


def preprocess_train(buf: bytes, bbox, rng: np.random.Generator,
                     as_u8: bool = False) -> np.ndarray:
    nj = native_jpeg_module()
    if nj is not None:
        try:
            # fused decode-and-crop: read the shape from the header, then
            # decode only the sampled window (decode_and_crop_jpeg parity,
            # imagenet_preprocessing.py:363-368)
            h, w = nj.shape(buf)
            y, x, ch, cw = sample_distorted_bbox(rng, h, w, bbox)
            cropped = nj.decode_crop(buf, y, x, ch, cw)
        except ValueError:
            cropped = None
    else:
        cropped = None
    if cropped is None:
        image = decode_jpeg(buf)
        h, w = image.shape[:2]
        y, x, ch, cw = sample_distorted_bbox(rng, h, w, bbox)
        cropped = image[y:y + ch, x:x + cw]
    if rng.random() < 0.5:
        cropped = cropped[:, ::-1]
    out = _resize_bilinear(np.ascontiguousarray(cropped),
                           DEFAULT_IMAGE_SIZE, DEFAULT_IMAGE_SIZE)
    return _round_u8(out) if as_u8 else out - CHANNEL_MEANS


def preprocess_eval(buf: bytes, as_u8: bool = False) -> np.ndarray:
    """Aspect-preserving resize to shorter side RESIZE_MIN (:438-480) +
    central crop (:375-394) + mean subtract (or the raw-pixel uint8
    wire with ``as_u8``).  Dispatches to the fused native pass (decode
    window → one tf-bilinear sampling) when built; Python/PIL fallback
    below."""
    nj = native_jpeg_module()
    if nj is not None and hasattr(nj, "eval_batch"):
        u8_native = as_u8 and nj.wire_u8_supported()
        out, ok = nj.eval_batch([buf], RESIZE_MIN, DEFAULT_IMAGE_SIZE,
                                DEFAULT_IMAGE_SIZE, CHANNEL_MEANS,
                                num_threads=1, out_u8=u8_native)
        if ok[0]:
            if as_u8 and not u8_native:  # stale-.so requantize (ok row)
                return _round_u8(out[0] + CHANNEL_MEANS)
            return out[0]
    image = decode_jpeg(buf)
    h, w = image.shape[:2]
    scale = RESIZE_MIN / min(h, w)
    nh, nw = int(round(h * scale)), int(round(w * scale))
    resized = _resize_bilinear(image, nh, nw)
    oy = (nh - DEFAULT_IMAGE_SIZE) // 2
    ox = (nw - DEFAULT_IMAGE_SIZE) // 2
    crop = resized[oy:oy + DEFAULT_IMAGE_SIZE, ox:ox + DEFAULT_IMAGE_SIZE]
    return _round_u8(crop) if as_u8 else crop - CHANNEL_MEANS


def parse_example_record(raw: bytes):
    """Returns (jpeg_bytes, label_int, bbox or None) — the
    _parse_example_proto contract (:156-223)."""
    feats = records.parse_example(raw)
    buf = feats["image/encoded"][0]
    label = int(np.asarray(feats["image/class/label"])[0]) - 1  # → [0,1000)
    bbox = None
    if "image/object/bbox/ymin" in feats and len(feats["image/object/bbox/ymin"]):
        bbox = np.stack([
            np.asarray(feats["image/object/bbox/ymin"], np.float32),
            np.asarray(feats["image/object/bbox/xmin"], np.float32),
            np.asarray(feats["image/object/bbox/ymax"], np.float32),
            np.asarray(feats["image/object/bbox/xmax"], np.float32),
        ], axis=1)
    return buf, label, bbox


def _record_stream(files, is_training: bool, rng: np.random.Generator,
                   interleave: int = 10):
    """File-shuffled, interleaved raw-record stream (≈ tf.data
    interleave(cycle_length=10), :290-310)."""
    while True:
        order = rng.permutation(len(files)) if is_training else range(len(files))
        readers: list = []
        it = iter(order)
        def refill():
            while len(readers) < interleave:
                try:
                    readers.append(records.read_tfrecord_file(files[next(it)]))
                except StopIteration:
                    return
        refill()
        while readers:
            for r in list(readers):
                try:
                    yield next(r)
                except StopIteration:
                    readers.remove(r)
            refill()
        if not is_training:
            return


def imagenet_input_fn(data_dir: str, is_training: bool, batch_size: int,
                      seed: int = 0, num_threads: Optional[int] = None,
                      process_id: Optional[int] = None,
                      process_count: Optional[int] = None,
                      drop_remainder: bool = True,
                      fast_dct: bool = False,
                      scaled_decode: bool = False,
                      stats: Optional[dict] = None,
                      wire: str = "float32", start_step: int = 0) -> Iterator:
    """Yields (images [B,224,224,3], labels int32 [B]) — plus a
    float32 validity mask [B] for eval with ``drop_remainder=False``.

    ``wire``: host→device batch format.  ``"float32"`` = mean-subtracted
    f32 (r1-r3 behavior); ``"uint8"`` = raw post-resize pixels rounded
    half-up — 4x fewer bytes per batch (RUN_r03 measured the f32 wire
    transfer-bound at 38 MB/batch) — with mean subtraction deferred to
    the compiled step (data/normalize.py imagenet_mean_subtract).

    ``stats``: pass a dict to collect per-batch timing from the native
    train path — keys py_s (GIL-held Python work: Example parse, crop
    sampling), native_s (GIL-released fused C++ decode) and batches are
    accumulated in place.  The Python share serializes across worker
    threads, so py_s per batch is the Amdahl floor on multi-core
    scaling (bench_input.py reports the derived ceiling).

    Eval modes:
      - ``drop_remainder=False`` (config default): eval FILES are
        sharded across processes, each host counts its records via
        header-seek (no payload I/O), hosts agree on the max batch
        count, and final/short batches are zero-padded with mask=0 —
        full 50k coverage, each example exactly once, no duplicated
        multi-host decode work.
      - ``drop_remainder=True``: every host reads the full eval set and
        drops the final partial batch (2-tuples; r1 behavior).
    """
    import jax
    process_id = jax.process_index() if process_id is None else process_id
    process_count = (jax.process_count() if process_count is None
                     else process_count)
    if is_training and start_step:
        # This pipeline's batch composition depends on decode-worker
        # timing (the shuffle buffer drains nondeterministically across
        # threads), so a bit-exact replay from step N is not defined —
        # and silently re-keying (the pre-data-service behavior) broke
        # the crash-exact guarantee on the flagship workload.  The
        # position-deterministic path exists: refuse loudly instead.
        raise ValueError(
            f"imagenet mid-stream resume (start_step={start_step}) is "
            f"not supported by the legacy threaded pipeline — its batch "
            f"order is decode-timing-dependent, so the resumed stream "
            f"cannot replay bit-exactly.  Use the sharded deterministic "
            f"data service (--input_service, the default), which makes "
            f"batch n a pure function of (seed, process, n)")
    if wire not in ("float32", "uint8"):
        raise ValueError(f"wire must be 'float32' or 'uint8', got {wire!r}")
    u8 = wire == "uint8"
    files = get_filenames(is_training, data_dir)
    pad_eval = (not is_training) and (not drop_remainder)
    # drop-mode eval must yield the same batch count on every host or
    # the collective eval_step deadlocks, so only padded eval shards its
    # files (train always shards, cifar_preprocessing.py:147-152)
    if (is_training or pad_eval) and process_count > 1:
        files = shard_for_process(files, process_id, process_count)
        if is_training and not files:
            files = get_filenames(is_training, data_dir)
    eval_batches = None
    if pad_eval:
        local_count = sum(records.count_tfrecord_records(f) for f in files)
        from dtf_tpu.data.pipeline import all_processes_max
        eval_batches = all_processes_max(-(-local_count // batch_size))
    num_threads = num_threads or min(8, (os.cpu_count() or 1) * 4)
    rng = np.random.default_rng(seed + 7919 * process_id)

    raw_q: queue.Queue = queue.Queue(maxsize=SHUFFLE_BUFFER // 4)
    out_q: queue.Queue = queue.Queue(maxsize=64)
    stop = threading.Event()
    # the lock is published through the stats dict so readers
    # (bench_input) can snapshot consistently with the writers
    stats_lock = threading.Lock()
    if stats is not None:
        stats["lock"] = stats_lock

    # Batched native fast path (train only): the reader's shuffle buffer
    # emits whole-batch CHUNKS of raw records, and each Python worker
    # owns a full batch end-to-end — parse + crop sampling (cheap,
    # header-only JPEG shape reads), then ONE fused C++ call doing
    # decode-crop-flip-resize-mean-subtract with the GIL released
    # (dtf_native.cpp dtf_jpeg_decode_crop_resize_batch).  Parallelism
    # is across batches; queue traffic is 2 hops per BATCH, not per
    # record (the per-record design lost ~half its throughput to queue
    # and GIL ping-pong).
    nj = native_jpeg_module()
    batch_native = (is_training and nj is not None
                    and hasattr(nj, "decode_crop_resize_batch"))
    # uint8 straight out of the C++ ops when the library has the wire;
    # a stale .so degrades to f32 + host requantize (_meansub_to_u8)
    u8_native = u8 and nj is not None and nj.wire_u8_supported()

    def reader():
        # shuffle buffer over raw records (:114-120)
        buffer: list = []
        chunk: list = []
        try:
            for raw in _record_stream(files, is_training, rng):
                if stop.is_set():
                    return
                if is_training:
                    buffer.append(raw)
                    if len(buffer) >= SHUFFLE_BUFFER:
                        idx = rng.integers(0, len(buffer))
                        buffer[idx], buffer[-1] = buffer[-1], buffer[idx]
                        if batch_native:
                            chunk.append(buffer.pop())
                            if len(chunk) == batch_size:
                                raw_q.put(chunk)
                                chunk = []
                        else:
                            raw_q.put(buffer.pop())
                else:
                    raw_q.put(raw)
            for raw in buffer:
                if batch_native:
                    chunk.append(raw)
                    if len(chunk) == batch_size:
                        raw_q.put(chunk)
                        chunk = []
                else:
                    raw_q.put(raw)
            # a final sub-batch chunk is dropped: training repeats
            # forever, so this only ever cuts the very tail of the
            # stream's last epoch pass
        finally:
            for _ in range(num_threads):
                raw_q.put(None)

    def _slow_item(buf, crop, flip):
        """Python fallback for images the batch decoder rejects."""
        image = decode_jpeg(buf)
        y, x, ch, cw = crop
        cropped = image[y:y + ch, x:x + cw]
        if flip:
            cropped = cropped[:, ::-1]
        out = _resize_bilinear(np.ascontiguousarray(cropped),
                               DEFAULT_IMAGE_SIZE, DEFAULT_IMAGE_SIZE)
        return _round_u8(out) if u8 else out - CHANNEL_MEANS

    # Fully-native batch path: parse + crop-sample + decode all happen
    # in ONE C++ call (dtf_train_example_batch) — the per-record Python
    # work that used to run here (Example parse, header reads, numpy
    # sampling) was the pipeline's measured GIL-held serial fraction.
    # Gate on the LIBRARY symbol, not the Python wrapper (which always
    # exists): a stale .so must fall back to the two-step native path
    # it still supports, not crash the first batch.
    def _lib_has_train_batch():
        from dtf_tpu import native as native_lib
        lib = native_lib.load()
        return lib is not None and hasattr(lib, "dtf_train_example_batch")

    full_native = batch_native and _lib_has_train_batch()

    def _python_record(raw, wrng):
        """Whole-record Python fallback (parse failures)."""
        buf, label, bbox = parse_example_record(raw)
        return preprocess_train(buf, bbox, wrng, as_u8=u8), label

    def batch_worker(wid: int):
        """One whole batch per iteration, end-to-end in C++ when the
        library provides the fused op; Python parse + fused decode
        otherwise."""
        import time as _time
        wrng = np.random.default_rng(seed + 104729 * (process_id + 1) + wid)

        def record_stats(py_s, native_s):
            if stats is not None:
                # dict read-modify-write is NOT atomic across threads
                with stats_lock:
                    stats["py_s"] = stats.get("py_s", 0.0) + py_s
                    stats["native_s"] = (stats.get("native_s", 0.0)
                                         + native_s)
                    stats["batches"] = stats.get("batches", 0) + 1

        while True:
            chunk = raw_q.get()
            if chunk is None or stop.is_set():
                out_q.put(None)
                return
            try:
                if full_native:
                    t0 = _time.perf_counter()
                    batch_seed = int(wrng.integers(0, 2**63))
                    t1 = _time.perf_counter()
                    images, labels, crops, flips, statuses = \
                        nj.train_example_batch(
                            chunk, batch_seed, DEFAULT_IMAGE_SIZE,
                            DEFAULT_IMAGE_SIZE, CHANNEL_MEANS,
                            num_threads=1, fast_dct=fast_dct,
                            scaled_decode=scaled_decode,
                            out_u8=u8_native)
                    if u8 and not u8_native:
                        images = _meansub_to_u8(images, statuses == 0)
                    t2 = _time.perf_counter()
                    for j in np.nonzero(statuses)[0]:
                        if statuses[j] == 1:  # parse/header failure
                            images[j], labels[j] = _python_record(
                                chunk[j], wrng)
                        else:  # decode failure: same crop/flip
                            buf, _, _ = parse_example_record(chunk[j])
                            images[j] = _slow_item(
                                buf, tuple(crops[j]), bool(flips[j]))
                    record_stats(t1 - t0, t2 - t1)
                    out_q.put((images, labels))
                    continue
                t0 = _time.perf_counter()
                bufs, labels, crops, flips, slow = [], [], [], [], {}
                for raw in chunk:
                    buf, label, bbox = parse_example_record(raw)
                    labels.append(label)
                    try:
                        h, w = nj.shape(buf)
                        crops.append(
                            sample_distorted_bbox(wrng, h, w, bbox))
                        flips.append(bool(wrng.random() < 0.5))
                    except ValueError:
                        # undecodable header → whole-image Python path
                        slow[len(bufs)] = preprocess_train(buf, bbox, wrng,
                                                       as_u8=u8)
                        crops.append((0, 0, 1, 1))
                        flips.append(False)
                    bufs.append(buf)
                t1 = _time.perf_counter()
                images, ok = nj.decode_crop_resize_batch(
                    bufs, crops, flips, DEFAULT_IMAGE_SIZE,
                    DEFAULT_IMAGE_SIZE, CHANNEL_MEANS, num_threads=1,
                    fast_dct=fast_dct, scaled_decode=scaled_decode,
                    out_u8=u8_native)
                if u8 and not u8_native:
                    images = _meansub_to_u8(images, ok)
                t2 = _time.perf_counter()
                record_stats(t1 - t0, t2 - t1)
                for j, img in slow.items():
                    images[j] = img
                for j in np.nonzero(~ok)[0]:
                    if j not in slow:
                        images[j] = _slow_item(bufs[j], crops[j],
                                               flips[j])
                out_q.put((images,
                           np.asarray(labels, np.int32)))
            except Exception as e:
                out_q.put(e)
                return

    def worker(wid: int):
        wrng = np.random.default_rng(seed + 104729 * (process_id + 1) + wid)
        while True:
            raw = raw_q.get()
            if raw is None or stop.is_set():
                out_q.put(None)
                return
            try:
                buf, label, bbox = parse_example_record(raw)
                img = (preprocess_train(buf, bbox, wrng, as_u8=u8)
                       if is_training else preprocess_eval(buf, as_u8=u8))
                out_q.put((img, label))
            except Exception as e:
                out_q.put(e)
                return

    threads = [threading.Thread(target=reader, daemon=True)]
    threads += [threading.Thread(target=batch_worker if batch_native
                                 else worker, args=(w,), daemon=True)
                for w in range(num_threads)]
    for t in threads:
        t.start()

    def _stop_pipeline():
        """Stop threads and join them.  Order matters: DRAIN the queues
        first (unblocking producers stuck on put()), THEN put the None
        wake-up sentinels — draining after would consume our own
        sentinels (or ones an exited reader left) and leave workers
        blocked on raw_q.get() past the join timeout."""
        stop.set()
        for q in (raw_q, out_q):  # unblock producers stuck on put()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        for _ in range(num_threads):  # wake workers stuck on get()
            try:
                raw_q.put_nowait(None)
            except queue.Full:
                break
        for t in threads:
            t.join(timeout=5.0)

    def _shutdown():
        """Interpreter-exit backstop: if the process exits while a
        daemon worker is inside the GIL-released C++ decode, CPython
        force-unwinds the thread (pthread_exit) when the foreign call
        returns — which aborts through the C++ frames (glibc
        'FATAL: exception not rethrown').  Stop the pipeline and wait
        for in-flight decodes instead."""
        _stop_pipeline()

    # Registered per pipeline, unregistered when the consuming
    # generator is exhausted or closed — a long test session creating
    # many iterators must not accumulate handlers (each pins its
    # queues/threads until process exit).
    import atexit
    atexit.register(_shutdown)

    def _teardown():
        # Same joins as _shutdown BEFORE unregistering it: an in-flight
        # GIL-released decode at interpreter exit is force-unwound
        # through the C++ frames the moment no one waits for it —
        # dropping the backstop without joining would re-open exactly
        # the crash it exists to prevent.
        _stop_pipeline()
        if not any(t.is_alive() for t in threads):
            atexit.unregister(_shutdown)  # else keep the backstop

    def gen_native():
        done_workers = 0
        try:
            while done_workers < num_threads:
                item = out_q.get()
                if item is None:
                    done_workers += 1
                    continue
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            _teardown()

    def gen():
        images = np.empty((batch_size, DEFAULT_IMAGE_SIZE, DEFAULT_IMAGE_SIZE,
                           NUM_CHANNELS), np.uint8 if u8 else np.float32)
        labels = np.empty((batch_size,), np.int32)
        filled = 0
        done_workers = 0
        yielded = 0
        try:
            while done_workers < num_threads:
                item = out_q.get()
                if item is None:
                    done_workers += 1
                    continue
                if isinstance(item, Exception):
                    raise item
                images[filled], labels[filled] = item
                filled += 1
                if filled == batch_size:
                    if pad_eval:
                        yield (images.copy(), labels.copy(),
                               np.ones((batch_size,), np.float32))
                    else:
                        yield images.copy(), labels.copy()
                    filled = 0
                    yielded += 1
            if pad_eval:
                # final partial batch zero-padded + fully-masked filler
                # batches up to the agreed cross-host count
                while yielded < eval_batches:
                    mask = np.zeros((batch_size,), np.float32)
                    mask[:filled] = 1.0
                    images[filled:] = 0.0
                    labels[filled:] = 0
                    yield images.copy(), labels.copy(), mask
                    filled = 0
                    yielded += 1
        finally:
            _teardown()

    return gen_native() if batch_native else gen()
