"""Synthetic data backend.

Parity with reference common.get_synth_input_fn (common.py:311-359):
one random batch — truncated normal images (mean 127, std 60, i.e. raw
pixel range) and uniform integer labels — repeated forever, bypassing
all preprocessing.  Used to find the input-pipeline-free throughput
upper bound and by the whole smoke-test matrix
(resnet_cifar_test.py:36-40).
"""

from __future__ import annotations

import numpy as np

from dtf_tpu.data.base import DatasetSpec


def _truncated_normal(rng, shape, mean, std):
    """Resample outside ±2σ, like tf.random.truncated_normal."""
    x = rng.standard_normal(shape)
    bad = np.abs(x) > 2.0
    while bad.any():
        x[bad] = rng.standard_normal(int(bad.sum()))
        bad = np.abs(x) > 2.0
    return (x * std + mean).astype(np.float32)


def synthetic_input_fn(spec: DatasetSpec, is_training: bool, batch_size: int,
                       seed: int = 0, dtype=np.float32,
                       start_step: int = 0):
    """Yields the same (images, labels) batch forever (train) or for one
    eval pass.  labels are int32 class ids; one-hot is applied by the
    loss layer when spec.one_hot.

    ``start_step`` exists for pipeline-position parity with the real
    input fns (crash-exact resume repositions its data stream here):
    the synthetic stream repeats one batch, so every position is
    identical and the argument is accepted but has no effect."""
    del start_step  # position-independent by construction
    rng = np.random.default_rng(seed)
    if spec.is_sequence:
        # token LM: random ids, next-token labels (shift left; the final
        # position wraps — harmless for synthetic throughput/smoke data)
        tokens = rng.integers(0, spec.num_classes,
                              size=(batch_size, spec.seq_len), dtype=np.int32)
        images = tokens
        labels = np.roll(tokens, -1, axis=1)
    else:
        images = _truncated_normal(
            rng, (batch_size,) + spec.image_shape, 127.0, 60.0).astype(dtype)
        labels = rng.integers(0, spec.num_classes - 1, size=(batch_size,),
                              dtype=np.int32)

    def gen():
        if is_training:
            while True:
                yield images, labels
        else:
            n = max(1, spec.num_eval // batch_size)
            for _ in range(n):
                yield images, labels

    return gen()
