"""Sharded deterministic ImageNet reader — batch (shard, k) is a pure
function of position.

The legacy threaded pipeline (data/imagenet.py) gets its throughput
from a shuffle buffer drained by racing decode workers — which makes
batch composition depend on thread timing, so a killed run cannot
replay its exact stream.  This reader inverts the design: the TFRecord
file set is partitioned into ``num_shards`` STATIC shards
(``files[shard::num_shards]``, the same positional rule as
process sharding), each shard builds a byte-offset index of its records
once (header seeks only, no payload I/O), and every batch is computed
from position-derived RNGs, mirroring the PR-4 cifar scheme:

    shuffle order of shard-local epoch e:  SeedSequence([seed, pid,
                                           shard, e])
    augmentation draws of batch (e, j):    SeedSequence([seed, pid,
                                           shard, e, j, 1])

so ``batch(k)`` — shard-local batch number ``k = e * batches_per_epoch
+ j`` — depends on nothing but ``(seed, process, shard, k)``.  A run
resumed at any position recomputes the exact batches the uninterrupted
run would have produced; a respawned worker re-enters the stream at its
recorded position with zero drift.

Decode path: full JPEG decode (native libjpeg when built, else PIL) →
numpy window crop → flip → PIL bilinear resize — ONE code path whether
or not the decode-once cache serves the pixels, so cached and uncached
runs are bit-identical by construction.  (The legacy pipeline's fused
decode-crop C++ op cannot feed a decode-once cache: its output already
has the epoch's crop baked in.)
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from dtf_tpu.data.imagenet import (CHANNEL_MEANS, DEFAULT_IMAGE_SIZE,
                                   NUM_CHANNELS, _resize_bilinear, _round_u8,
                                   decode_jpeg, get_filenames,
                                   parse_example_record,
                                   sample_distorted_bbox)
from dtf_tpu.data.service.cache import DecodeCache


def index_tfrecord_file(path: str) -> List[Tuple[int, int]]:
    """[(payload_offset, payload_length), ...] for one TFRecord file —
    header seeks only (the framing stores the length up front), so
    indexing costs O(records) tiny reads, not a full pass over pixels."""
    out: List[Tuple[int, int]] = []
    with open(path, "rb") as f:
        f.seek(0, 2)
        end = f.tell()
        pos = 0
        while pos < end:
            f.seek(pos)
            header = f.read(12)
            if len(header) < 12:
                raise IOError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            if pos + 12 + length + 4 > end:
                raise IOError(f"{path}: truncated record body")
            out.append((pos + 12, length))
            pos += 12 + length + 4
    return out


class ShardReader:
    """One static shard of the TFRecord file set, served as
    position-derived batches.

    ``files`` is the PER-PROCESS file list (multi-host runs shard files
    across processes first, exactly like the legacy pipeline); this
    reader takes the ``shard``-th positional slice of it.
    """

    def __init__(self, files: List[str], shard: int, num_shards: int,
                 batch_size: int, seed: int = 0, process_id: int = 0,
                 wire: str = "uint8", cache: Optional[DecodeCache] = None):
        if not 0 <= shard < num_shards:
            raise ValueError(f"shard {shard} outside [0, {num_shards})")
        if wire not in ("float32", "uint8"):
            raise ValueError(f"wire must be 'float32' or 'uint8', got "
                             f"{wire!r}")
        self.shard = int(shard)
        self.num_shards = int(num_shards)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.process_id = int(process_id)
        self.u8 = wire == "uint8"
        self.cache = cache
        self.files = files[shard::num_shards]
        if not self.files:
            raise ValueError(
                f"shard {shard}: num_shards {num_shards} exceeds the "
                f"{len(files)} input files — each shard needs at least "
                f"one file (lower --input_num_shards; it is part of "
                f"the stream identity, so pick it once per run)")
        # global record index: (file number, payload offset, length)
        self.index: List[Tuple[int, int, int]] = []
        for fi, path in enumerate(self.files):
            for off, length in index_tfrecord_file(path):
                self.index.append((fi, off, length))
        self.batches_per_epoch = len(self.index) // self.batch_size
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"shard {shard} holds {len(self.index)} records, fewer "
                f"than the batch size {batch_size}; use fewer shards")
        self._handles: Dict[int, object] = {}
        # (epoch, permutation) memo: order() is pure, so one entry
        # suffices — sequential consumption regenerates the (on real
        # ImageNet, ~320k-element) permutation once per epoch, not once
        # per batch (the cifar pipeline keeps the same memo)
        self._order: Optional[Tuple[int, np.ndarray]] = None

    # -- record access --------------------------------------------------
    def _raw(self, record_idx: int) -> bytes:
        fi, off, length = self.index[record_idx]
        f = self._handles.get(fi)
        if f is None:
            f = self._handles[fi] = open(self.files[fi], "rb")
        f.seek(off)
        return f.read(length)

    def _decoded(self, record_idx: int):
        """(full decoded uint8 image, label, bbox) — decode-once cache
        tier first, libjpeg/PIL on miss (populating the cache)."""
        if self.cache is not None:
            hit = self.cache.get(record_idx)
            if hit is not None:
                return hit
        buf, label, bbox = parse_example_record(self._raw(record_idx))
        image = decode_jpeg(buf)
        if self.cache is not None:
            self.cache.put(record_idx, image, label, bbox)
        return image, label, bbox

    # -- position-derived batches ---------------------------------------
    def order(self, epoch: int) -> np.ndarray:
        """Shuffle order of shard-local epoch ``epoch`` — a pure
        function of (seed, process, shard, epoch), memoized for the
        epoch the caller is currently consuming."""
        epoch = int(epoch)
        if self._order is None or self._order[0] != epoch:
            self._order = (epoch, np.random.default_rng(
                np.random.SeedSequence(
                    [self.seed, self.process_id, self.shard,
                     epoch])).permutation(len(self.index)))
        return self._order[1]

    def batch(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Shard-local batch ``k`` — images [B, 224, 224, 3] (uint8 raw
        pixels or mean-subtracted f32, per ``wire``) + int32 labels.
        Pure in ``k``: calling it twice, in any order, from any process
        lifetime, yields bit-identical arrays."""
        epoch, j = divmod(int(k), self.batches_per_epoch)
        sel = self.order(epoch)[j * self.batch_size:
                                (j + 1) * self.batch_size]
        brng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, self.process_id, self.shard, epoch, j, 1]))
        images = np.empty((self.batch_size, DEFAULT_IMAGE_SIZE,
                           DEFAULT_IMAGE_SIZE, NUM_CHANNELS),
                          np.uint8 if self.u8 else np.float32)
        labels = np.empty((self.batch_size,), np.int32)
        for i, ridx in enumerate(sel):
            image, label, bbox = self._decoded(int(ridx))
            h, w = image.shape[:2]
            y, x, ch, cw = sample_distorted_bbox(brng, h, w, bbox)
            crop = image[y:y + ch, x:x + cw]
            if brng.random() < 0.5:
                crop = crop[:, ::-1]
            out = _resize_bilinear(np.ascontiguousarray(crop),
                                   DEFAULT_IMAGE_SIZE, DEFAULT_IMAGE_SIZE)
            images[i] = _round_u8(out) if self.u8 else out - CHANNEL_MEANS
            labels[i] = label
        return images, labels

    def cache_stats(self) -> Tuple[int, int]:
        """(hits, lookups) of the cache tier; (0, 0) when disabled."""
        if self.cache is None:
            return (0, 0)
        return (self.cache.hits, self.cache.lookups)

    def close(self) -> None:
        for f in self._handles.values():
            try:
                f.close()
            except OSError:
                pass
        self._handles.clear()
        if self.cache is not None:
            self.cache.close()


def make_reader(data_dir: str, shard: int, num_shards: int,
                batch_size: int, seed: int = 0, process_id: int = 0,
                process_count: int = 1, wire: str = "uint8",
                cache_dir: str = "", cache_limit_bytes: int = 0
                ) -> ShardReader:
    """ShardReader over the production train-file layout, with the
    per-process file split applied first (multi-host parity with the
    legacy pipeline) and the decode-once cache attached when
    ``cache_dir`` is set."""
    from dtf_tpu.data.pipeline import shard_for_process
    files = get_filenames(True, data_dir)
    if process_count > 1:
        files = shard_for_process(files, process_id, process_count) or files
    cache = (DecodeCache(cache_dir, shard, cache_limit_bytes,
                         num_shards=num_shards, process_id=process_id,
                         process_count=process_count)
             if cache_dir else None)
    return ShardReader(files, shard, num_shards, batch_size, seed=seed,
                       process_id=process_id, wire=wire, cache=cache)
