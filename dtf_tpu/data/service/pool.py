"""Multi-process shard worker pool + deterministic merged stream.

The host-side data service: ``num_shards`` ShardReaders served by
``num_workers`` SPAWNED processes (processes, not threads — the Amdahl
serial fraction bench_input.py measures is GIL-held Python, so thread
pools stop scaling at one core's worth of Python), merged into one
stream whose order is a pure function of position:

    merged batch n  ==  shard (n % num_shards), shard-local batch
                        (n // num_shards)

Round-robin interleave over a static shard->worker assignment
(``shards[w::num_workers]``) makes the merged stream invariant to the
WORKER count: workers only decide who computes a batch, never what the
batch is (ShardReader.batch is pure in position).  ``start_step=n``
therefore replays the exact mid-epoch suffix of the stream — the piece
that makes killed-at-K resume bit-exact on imagenet.

Supervision: the pool owns its workers.  A worker that dies (chaos
``reader_crash@batch:N``, a real OOM-kill) is respawned at its recorded
per-shard positions with a fresh queue; determinism guarantees the
respawned worker recomputes exactly the batches the dead one would
have produced, so the merged stream is unchanged.  Respawns are
budgeted (a deterministically-crashing reader must fail loudly, not
spin), counted on the obs registry, and traced.

Observability: ``data_reader_lag_s`` (time the consumer blocked waiting
for the next batch) and ``data_cache_hit_ratio`` land on the default
obs registry every batch, and a report-only ReaderLagWatchdog emits a
structured ``reader_lag`` anomaly when the lag regresses — the
input-stall signal the PR-2 watchdogs exist to surface.
"""

from __future__ import annotations

import atexit
import logging
import multiprocessing as mp
import os
import queue as queue_mod
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from dtf_tpu import chaos
from dtf_tpu.obs import trace

log = logging.getLogger("dtf_tpu")

# queue item tags (first tuple element is the shard id for batches)
_ERROR = "__error__"


def _supervisor_event(event: str, **attrs) -> None:
    """Append one record to the launcher's ``supervisor_events.jsonl``
    (via cli/launch.py SupervisorEventLog — ONE schema for every
    supervision record) when this rank runs under the launcher —
    post-mortems then see reader-restart decisions WITH their data
    positions next to the supervisor's own rank-restart records.  The
    launcher exports its log dir as DTF_HEARTBEAT_DIR; standalone runs
    (no env) skip silently, and SupervisorEventLog already swallows a
    full disk."""
    sup_dir = os.environ.get("DTF_HEARTBEAT_DIR")
    if not sup_dir:
        return
    from dtf_tpu.cli.launch import SupervisorEventLog
    SupervisorEventLog(sup_dir).emit(
        event, rank=int(os.environ.get("DTF_PROCESS_ID", "0")), **attrs)


def shard_positions(step: int, num_shards: int) -> List[int]:
    """Per-shard next-batch positions after ``step`` merged batches —
    the host_state payload a checkpoint carries so the resume contract
    is explicit in the manifest (the positions are also derivable from
    the step alone; carrying them makes the manifest self-describing
    and lets a reader of the manifest audit the math)."""
    step = int(step)
    num_shards = int(num_shards)
    return [step // num_shards + (1 if s < step % num_shards else 0)
            for s in range(num_shards)]


def _worker_main(payload: dict, out_q) -> None:
    """Shard-worker process body: build this worker's ShardReaders and
    produce batches round-robin over its shards, ascending k per shard,
    forever (training streams are infinite).  Every item carries its
    (shard, k) tag plus cumulative cache counters; backpressure is the
    bounded queue."""
    # keep the spawned child off any accelerator: readers are pure
    # numpy/PIL/libjpeg and must never grab a TPU chip from the parent
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from dtf_tpu.data.service.reader import make_reader
        readers = {}
        for s in payload["shards"]:
            readers[s] = make_reader(
                payload["data_dir"], s, payload["num_shards"],
                payload["batch_size"], seed=payload["seed"],
                process_id=payload["process_id"],
                process_count=payload["process_count"],
                wire=payload["wire"], cache_dir=payload["cache_dir"],
                cache_limit_bytes=payload["cache_limit_bytes"])
        ks = dict(payload["start_ks"])
        while True:
            for s in payload["shards"]:
                images, labels = readers[s].batch(ks[s])
                hits, lookups = readers[s].cache_stats()
                out_q.put((s, ks[s], images, labels, hits, lookups))
                ks[s] += 1
    except Exception as e:  # noqa: BLE001 — surfaced in the parent
        import traceback
        try:
            out_q.put((_ERROR, repr(e), traceback.format_exc()))
        except Exception:  # noqa: BLE001 — queue torn down under us
            pass


class ServiceStream:
    """The merged deterministic stream (iterator of (images, labels)).

    ``num_workers == 0`` runs every ShardReader inline (no subprocess):
    same stream, no spawn cost — the right default for tests and
    single-core hosts.  ``num_workers >= 1`` spawns worker processes,
    each owning the static shard slice ``shards[w::num_workers]``.

    LOCK DISCIPLINE: the stream has ONE consumer thread by contract
    (positions/buffers are unguarded single-thread state), but
    ``close()`` is re-entrant from elsewhere — the atexit hook, a
    supervisor's teardown racing the consumer — so the closed latch is
    guarded by ``_close_lock`` (declared below, enforced by
    tools/dtflint lock-guard): the close-once check-and-set must not
    race a second closer into double-terminating workers mid-join.
    """

    _GUARDED_BY = {"_closed": "_close_lock"}

    MAX_RESPAWNS = 8
    GET_TIMEOUT_S = 0.5

    def __init__(self, data_dir: str, batch_size: int, *, seed: int = 0,
                 num_shards: int = 1, num_workers: int = 0,
                 process_id: int = 0, process_count: int = 1,
                 wire: str = "uint8", cache_dir: str = "",
                 cache_limit_bytes: int = 0, start_step: int = 0,
                 registry=None, lag_watchdog=None):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if start_step < 0:
            raise ValueError(f"start_step must be >= 0, got {start_step}")
        self.num_shards = int(num_shards)
        if num_workers < 0:
            # auto (the flag default): one worker per host core, capped
            # by the shard count — inline on a single-core host, where
            # a lone worker only adds spawn + pickle overhead.  Safe to
            # auto-size (and to differ across a resume) because worker
            # count never changes the stream.
            cores = os.cpu_count() or 1
            num_workers = 0 if cores < 2 else cores
        self.num_workers = min(int(num_workers), self.num_shards)
        self._n = int(start_step)  # next merged batch position
        # next shard-local batch each shard owes the merged stream
        self._need: Dict[int, int] = dict(
            enumerate(shard_positions(start_step, num_shards)))
        self._payload_base = dict(
            data_dir=data_dir, num_shards=self.num_shards,
            batch_size=int(batch_size), seed=int(seed),
            process_id=int(process_id), process_count=int(process_count),
            wire=wire, cache_dir=cache_dir,
            cache_limit_bytes=int(cache_limit_bytes))
        self._close_lock = threading.Lock()
        self._closed = False
        self.respawns = 0
        # obs wiring (default registry unless a bench injects its own)
        if registry is None:
            from dtf_tpu.obs.registry import default_registry
            registry = default_registry()
        self._lag_gauge = registry.gauge("data_reader_lag_s", unit="s")
        self._hit_gauge = registry.gauge("data_cache_hit_ratio")
        self._respawn_counter = registry.counter("data_reader_respawns")
        if lag_watchdog is None:
            from dtf_tpu.obs.watchdog import ReaderLagWatchdog
            lag_watchdog = ReaderLagWatchdog()
        self._lag_watchdog = lag_watchdog
        # (hits, lookups) high-water per shard — cumulative counters
        # ride every queue item; the ratio aggregates across shards
        self._cache_stats: Dict[int, Tuple[int, int]] = {}

        if self.num_workers == 0:
            from dtf_tpu.data.service.reader import make_reader
            self._readers = {
                s: make_reader(data_dir, s, self.num_shards,
                               int(batch_size), seed=int(seed),
                               process_id=int(process_id),
                               process_count=int(process_count),
                               wire=wire, cache_dir=cache_dir,
                               cache_limit_bytes=int(cache_limit_bytes))
                for s in range(self.num_shards)}
        else:
            self._ctx = mp.get_context("spawn")
            self._owner = {s: s % self.num_workers
                           for s in range(self.num_shards)}
            self._procs: List[Optional[mp.process.BaseProcess]] = \
                [None] * self.num_workers
            self._queues: List[Optional[object]] = [None] * self.num_workers
            # parent-side reorder buffer: {(shard, k): (images, labels)}
            self._buf: Dict[Tuple[int, int], Tuple[np.ndarray,
                                                   np.ndarray]] = {}
            for w in range(self.num_workers):
                self._spawn(w)
            atexit.register(self.close)

    # -- worker lifecycle ----------------------------------------------
    def _worker_shards(self, w: int) -> List[int]:
        return [s for s in range(self.num_shards) if self._owner[s] == w]

    def _spawn(self, w: int) -> None:
        shards = self._worker_shards(w)
        payload = dict(self._payload_base, shards=shards,
                       start_ks={s: self._need[s] for s in shards})
        q = self._ctx.Queue(maxsize=2 * len(shards) + 2)
        p = self._ctx.Process(target=_worker_main, args=(payload, q),
                              daemon=True, name=f"dtf-data-worker-{w}")
        p.start()
        self._procs[w] = p
        self._queues[w] = q

    def _respawn(self, w: int, reason: str) -> None:
        self.respawns += 1
        self._respawn_counter.inc()
        if self.respawns > self.MAX_RESPAWNS:
            raise RuntimeError(
                f"data-service worker {w} died {self.respawns} times "
                f"(last: {reason}) — exceeding the respawn budget; the "
                f"reader is failing deterministically")
        p = self._procs[w]
        exitcode = getattr(p, "exitcode", None)
        shards = self._worker_shards(w)
        # drop the dead worker's buffered batches: the respawned worker
        # recomputes them identically from its recorded positions, and
        # a half-delivered queue must not leave gaps behind kept items
        for key in [key for key in self._buf if key[0] in shards]:
            del self._buf[key]
        try:
            p.kill()
        except Exception:  # noqa: BLE001 — already dead
            pass
        p.join(timeout=5.0)
        q = self._queues[w]
        try:
            q.close()
            q.cancel_join_thread()
        except Exception:  # noqa: BLE001
            pass
        log.warning("data service: worker %d died (%s, exit %s) — "
                    "respawning at positions %s", w, reason, exitcode,
                    {s: self._need[s] for s in shards})
        trace.event("reader_respawn", worker=w, exitcode=exitcode,
                    reason=reason, positions=[self._need[s]
                                              for s in shards])
        # the restart decision, with its data positions, lands in the
        # launcher's supervisor_events.jsonl: the post-mortem view of
        # "worker 2 died at shard 3 batch 17" next to the supervisor's
        # rank-level restart records
        _supervisor_event(
            "reader_crash", worker=w, exitcode=exitcode, reason=reason,
            respawns=self.respawns,
            shard_positions={str(s): int(self._need[s]) for s in shards})
        self._spawn(w)

    # -- merged stream --------------------------------------------------
    def _fetch_pooled(self, s: int, k: int):
        w = self._owner[s]
        while True:
            item = self._buf.pop((s, k), None)
            if item is not None:
                return item
            try:
                got = self._queues[w].get(timeout=self.GET_TIMEOUT_S)
            except queue_mod.Empty:
                p = self._procs[w]
                if not p.is_alive():
                    self._respawn(w, "worker process dead")
                continue
            except Exception as e:  # noqa: BLE001 — torn pickle mid-kill
                self._respawn(w, f"queue read failed: {e!r}")
                continue
            if got[0] == _ERROR:
                # a reader exception is deterministic (corrupt shard,
                # bad config) — respawning would fail identically
                raise RuntimeError(
                    f"data-service worker {w} failed: {got[1]}\n{got[2]}")
            gs, gk, images, labels, hits, lookups = got
            self._cache_stats[gs] = (hits, lookups)
            if gk < self._need[gs]:
                continue  # stale duplicate from a pre-respawn overlap
            self._buf[(gs, gk)] = (images, labels)

    def __iter__(self):
        return self

    def __next__(self):
        # dtflint: disable=lock-guard (monotonic latch: a racy read
        # costs at most one extra batch before StopIteration; taking
        # _close_lock per batch would put a lock on the data hot path)
        if self._closed:
            raise StopIteration
        n = self._n
        s = n % self.num_shards
        k = n // self.num_shards
        if chaos.reader_crash(n):
            # kill the owning shard worker AS the consumer reaches this
            # batch — the supervisor respawn above must make the fault
            # invisible to the stream
            if self.num_workers:
                self._procs[self._owner[s]].kill()
            else:
                log.warning("chaos reader_crash@batch:%d ignored: the "
                            "inline reader has no worker process", n)
        t0 = time.perf_counter()
        if self.num_workers == 0:
            images, labels = self._readers[s].batch(k)
            self._cache_stats[s] = self._readers[s].cache_stats()
        else:
            images, labels = self._fetch_pooled(s, k)
        lag = time.perf_counter() - t0
        self._lag_gauge.set(lag)
        self._lag_watchdog.observe(n, lag)
        hits = sum(h for h, _ in self._cache_stats.values())
        lookups = sum(lk for _, lk in self._cache_stats.values())
        if lookups:
            self._hit_gauge.set(hits / lookups)
        self._n = n + 1
        self._need[s] = k + 1
        return images, labels

    @property
    def position(self) -> int:
        """Next merged batch index (== the global step the next batch
        feeds, for a stream built with input_fn(start_step=step))."""
        return self._n

    def cache_stats(self) -> Tuple[int, int]:
        """Cumulative (hits, lookups) across every shard since the
        stream was built — snapshot before/after a window to get a
        windowed ratio (the bench does)."""
        return (sum(h for h, _ in self._cache_stats.values()),
                sum(lk for _, lk in self._cache_stats.values()))

    def cache_hit_ratio(self) -> float:
        """Lifetime hit ratio (the ``data_cache_hit_ratio`` gauge):
        cold-start misses included, so a warm steady state converges
        toward 1.0 from below."""
        hits, lookups = self.cache_stats()
        return hits / lookups if lookups else 0.0

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        if self.num_workers == 0:
            for r in self._readers.values():
                r.close()
        else:
            for p in self._procs:
                if p is not None:
                    try:
                        p.terminate()
                    except Exception:  # noqa: BLE001
                        pass
            for p in self._procs:
                if p is not None:
                    p.join(timeout=5.0)
                    if p.is_alive():
                        p.kill()
                        p.join(timeout=5.0)
            for q in self._queues:
                if q is not None:
                    try:
                        q.close()
                        q.cancel_join_thread()
                    except Exception:  # noqa: BLE001
                        pass
            atexit.unregister(self.close)


def service_input_fn(data_dir: str, batch_size: int, *, seed: int = 0,
                     num_shards: int = 1, num_workers: int = 0,
                     process_id: Optional[int] = None,
                     process_count: Optional[int] = None,
                     wire: str = "uint8", cache_dir: str = "",
                     cache_limit_mb: int = 0,
                     start_step: int = 0) -> ServiceStream:
    """The data-service TRAIN input_fn (imagenet): a ServiceStream
    yielding (images, labels) host batches, position-deterministic and
    resumable via ``start_step`` (bit-exact, closing the PR-4 imagenet
    leftover).  Eval stays on data/imagenet.py — it is one ordered pass
    with no augmentation, so there is nothing to make deterministic."""
    if process_id is None or process_count is None:
        import jax
        process_id = (jax.process_index() if process_id is None
                      else process_id)
        process_count = (jax.process_count() if process_count is None
                         else process_count)
    return ServiceStream(
        data_dir, batch_size, seed=seed, num_shards=num_shards,
        num_workers=num_workers, process_id=process_id,
        process_count=process_count, wire=wire, cache_dir=cache_dir,
        cache_limit_bytes=int(cache_limit_mb) << 20,
        start_step=start_step)
