"""Decode-once cache tier — per-shard, mmap-backed, single-writer.

A tf.data-service-style host cache of DECODED images: JPEG decode is
the dominant per-record cost of the ImageNet pipeline, and it produces
the same pixels every epoch — only the crop/flip/resize augmentation
changes.  This cache stores the full decoded uint8 image (plus label
and the first ground-truth bbox, the only one the crop sampler reads)
the first time a record is decoded, so epoch >= 2 — and any other
reader of the same shard on this host — skips libjpeg entirely.

Layout (one pair of files per shard under the cache directory; the
filename encodes the full shard identity — shard/num_shards and the
per-process file split — because the cache key is the SHARD-LOCAL
record index: the same directory reused with a different sharding must
produce a fresh cache, never serve another partition's pixels):

    shard{S}of{N}.p{P}of{C}.data
                    raw uint8 pixel payloads, appended in put() order
    shard{S}of{N}.p{P}of{C}.idx
                    fixed 48-byte index entries
                    <record qq iiii 4f>: record_idx, data offset,
                    h, w, label, has_bbox, bbox(ymin,xmin,ymax,xmax)

Ownership: shard -> worker is a static assignment in the service pool,
so each cache pair has exactly ONE writer process — no cross-process
locking.  Reads go through an mmap of the data file (remapped lazily
when the file has grown), so a respawned worker — or a second training
run over the same dataset — reuses everything already decoded.

Crash safety: the index entry is appended (and flushed) only AFTER its
payload bytes are durably written, and load() ignores a torn final
index entry and any entry pointing past the end of the data file — a
worker SIGKILLed mid-put costs at most that one record.

Bounded: ``limit_bytes`` caps the data file; once the next payload
would not fit, the cache stops inserting (those records simply decode
every epoch) — a loud log line records the saturation once.
"""

from __future__ import annotations

import logging
import mmap
import os
import struct
from typing import Dict, Optional, Tuple

import numpy as np

log = logging.getLogger("dtf_tpu")

# record_idx, offset: int64; h, w, label, has_bbox: int32; bbox: 4 x f32
_ENTRY = struct.Struct("<qqiiii4f")
ENTRY_SIZE = _ENTRY.size  # 48


class DecodeCache:
    """Decode-once cache for ONE shard (single writer, many readers)."""

    def __init__(self, directory: str, shard: int, limit_bytes: int,
                 num_shards: int = 1, process_id: int = 0,
                 process_count: int = 1):
        os.makedirs(directory, exist_ok=True)
        self.shard = int(shard)
        self.limit_bytes = int(limit_bytes)
        stem = (f"shard{int(shard)}of{int(num_shards)}"
                f".p{int(process_id)}of{int(process_count)}")
        self.data_path = os.path.join(directory, f"{stem}.data")
        self.idx_path = os.path.join(directory, f"{stem}.idx")
        # index: record_idx -> (offset, h, w, label, bbox or None)
        self._index: Dict[int, Tuple[int, int, int, int,
                                     Optional[np.ndarray]]] = {}
        self._data = open(self.data_path, "ab")
        self._idx = open(self.idx_path, "ab")
        self._mm: Optional[mmap.mmap] = None
        self._mm_size = 0
        self._full_logged = False
        self.hits = 0
        self.lookups = 0
        self._load()

    # -- persistence ----------------------------------------------------
    def _load(self) -> None:
        """Rebuild the in-memory index from the idx file, dropping a
        torn tail entry and entries whose payload the data file does
        not fully contain (the mid-put crash window)."""
        try:
            with open(self.idx_path, "rb") as f:
                blob = f.read()
        except OSError:
            return
        data_size = os.path.getsize(self.data_path)
        usable = len(blob) - len(blob) % ENTRY_SIZE
        for pos in range(0, usable, ENTRY_SIZE):
            (ridx, off, h, w, label, has_bbox,
             b0, b1, b2, b3) = _ENTRY.unpack_from(blob, pos)
            if off + h * w * 3 > data_size:
                break  # payload torn — this and anything after is suspect
            bbox = (np.array([[b0, b1, b2, b3]], np.float32)
                    if has_bbox else None)
            self._index[ridx] = (off, h, w, label, bbox)

    def _map(self, end: int) -> mmap.mmap:
        """The data-file mmap, remapped when an entry lies past the
        current mapping (the file grows append-only).  The superseded
        mapping is NOT closed here: get() hands out zero-copy views
        into it, and closing a mmap with live buffer exports raises
        BufferError — dropping the reference lets the GC reclaim it
        once the last view dies."""
        if self._mm is None or end > self._mm_size:
            self._data.flush()
            size = os.path.getsize(self.data_path)
            with open(self.data_path, "rb") as f:
                self._mm = mmap.mmap(f.fileno(), size,
                                     access=mmap.ACCESS_READ)
            self._mm_size = size
        return self._mm

    # -- cache API ------------------------------------------------------
    def __len__(self) -> int:
        return len(self._index)

    def get(self, record_idx: int):
        """(image uint8 HWC view, label, bbox or None), or None on miss.
        The image is a zero-copy mmap view — callers crop/copy it, never
        mutate it."""
        self.lookups += 1
        entry = self._index.get(int(record_idx))
        if entry is None:
            return None
        off, h, w, label, bbox = entry
        mm = self._map(off + h * w * 3)
        img = np.frombuffer(mm, np.uint8, h * w * 3, off).reshape(h, w, 3)
        self.hits += 1
        return img, label, bbox

    def put(self, record_idx: int, image: np.ndarray, label: int,
            bbox: Optional[np.ndarray]) -> bool:
        """Insert one decoded image; False (and no write) when the
        record is already cached or the byte bound is reached."""
        record_idx = int(record_idx)
        if record_idx in self._index:
            return False
        image = np.ascontiguousarray(image, np.uint8)
        h, w = image.shape[:2]
        off = self._data.tell()
        if self.limit_bytes and off + image.nbytes > self.limit_bytes:
            if not self._full_logged:
                self._full_logged = True
                log.warning(
                    "decode cache shard %d is full (%d bytes); further "
                    "records decode every epoch", self.shard, off)
            return False
        # payload first, durably, THEN the index entry that blesses it
        self._data.write(image.tobytes())
        self._data.flush()
        has_bbox = bbox is not None and len(bbox)
        b = (np.asarray(bbox, np.float32)[0] if has_bbox
             else np.zeros((4,), np.float32))
        self._idx.write(_ENTRY.pack(record_idx, off, h, w, int(label),
                                    1 if has_bbox else 0,
                                    float(b[0]), float(b[1]),
                                    float(b[2]), float(b[3])))
        self._idx.flush()
        self._index[record_idx] = (
            off, h, w, int(label),
            np.array([b], np.float32) if has_bbox else None)
        return True

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                pass  # a caller still holds a view; the GC reclaims it
            self._mm = None
        for f in (self._data, self._idx):
            try:
                f.close()
            except OSError:
                pass
