"""Host-side data service: multi-process sharded deterministic readers
with a decode-once cache tier.

Why this exists (BENCH_r05): one host core supplies ~278 images/s while
a chip demands 2590 — ~9.3 cores per chip — and the remaining serial
fraction of the legacy pipeline is GIL-held Python, so threads cannot
close the gap.  This package scales decode across spawned PROCESSES and
makes every batch a pure function of position, which simultaneously
closes the PR-4 correctness leftover: killed-at-K resume on imagenet is
bit-exact, not best-effort re-keyed.

Pieces (see each module's docstring for the full design):

  reader.ShardReader   one static shard of the TFRecord file set,
                       served as position-derived batches
  cache.DecodeCache    per-shard mmap-backed decode-once cache
  pool.ServiceStream   worker-pool supervisor + deterministic
                       round-robin merged stream (the input_fn surface)
"""

from dtf_tpu.data.service.cache import DecodeCache  # noqa: F401
from dtf_tpu.data.service.pool import (ServiceStream,  # noqa: F401
                                       service_input_fn, shard_positions)
from dtf_tpu.data.service.reader import (ShardReader,  # noqa: F401
                                         index_tfrecord_file, make_reader)
