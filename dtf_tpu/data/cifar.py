"""CIFAR-10 binary input pipeline.

Parity with reference cifar_preprocessing.py:
  - fixed-length records: 1 label byte + 3072 image bytes CHW
    (:30-33), files data_batch_{1..5}.bin / test_batch.bin under
    `cifar-10-batches-bin` (:102-114)
  - train augmentation: pad to 40×40 (resize_with_crop_or_pad ≡
    zero-pad), random 32×32 crop, random horizontal flip (:84-96)
  - per_image_standardization: (x-mean)/max(stddev, 1/√N) (:98)
  - per-process shard-by-file (:147-152), full-dataset shuffle
    (process_record_dataset shuffle_buffer=NUM_IMAGES)

TPU-first shape: the dataset is 150 MB — it is loaded once into host
memory and batches are assembled with vectorized numpy (no per-record
op graph), which outruns the reference's generic record pipeline by
construction.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np

from dtf_tpu.data.base import CIFAR10
from dtf_tpu.data.pipeline import shard_for_process

HEIGHT = WIDTH = 32
NUM_CHANNELS = 3
RECORD_BYTES = HEIGHT * WIDTH * NUM_CHANNELS + 1
NUM_DATA_FILES = 5


def get_filenames(is_training: bool, data_dir: str):
    """Reference get_filenames (:102-114), including the assert on the
    extracted directory layout."""
    if "cifar-10-batches-bin" not in data_dir:
        data_dir = os.path.join(data_dir, "cifar-10-batches-bin")
    if not os.path.isdir(data_dir):
        raise FileNotFoundError(
            f"CIFAR-10 binary directory not found: {data_dir}; download and "
            f"extract cifar-10-binary.tar.gz")
    if is_training:
        return [os.path.join(data_dir, f"data_batch_{i}.bin")
                for i in range(1, NUM_DATA_FILES + 1)]
    return [os.path.join(data_dir, "test_batch.bin")]


def write_binary_file(path: str, images: np.ndarray,
                      labels: np.ndarray) -> None:
    """Write records in the CIFAR binary wire format: 1 label byte +
    3072 CHW image bytes each (cifar_preprocessing.py:30-33).  The
    inverse of :func:`load_records`; used by tests and run_record.py to
    synthesize datasets the production reader consumes."""
    images = np.asarray(images, np.uint8)
    labels = np.asarray(labels)
    n = len(labels)
    recs = np.zeros((n, RECORD_BYTES), np.uint8)
    recs[:, 0] = labels
    recs[:, 1:] = images.transpose(0, 3, 1, 2).reshape(n, -1)
    with open(path, "wb") as f:
        f.write(recs.tobytes())


def load_records(filenames, dtype=np.float32) -> Tuple[np.ndarray, np.ndarray]:
    """Parses fixed-length records → (images HWC ``dtype``, labels
    int32).  CHW→HWC transpose per reference parse_record (:43-75).
    ``dtype=np.uint8`` keeps the raw pixels (the uint8-wire mode — 4x
    less host memory and memcpy per batch)."""
    blobs = []
    for fn in filenames:
        raw = np.fromfile(fn, dtype=np.uint8)
        if raw.size % RECORD_BYTES:
            raise IOError(f"{fn}: size {raw.size} not a multiple of "
                          f"{RECORD_BYTES}")
        blobs.append(raw.reshape(-1, RECORD_BYTES))
    records = np.concatenate(blobs)
    labels = records[:, 0].astype(np.int32)
    images = (records[:, 1:]
              .reshape(-1, NUM_CHANNELS, HEIGHT, WIDTH)
              .transpose(0, 2, 3, 1)
              .astype(dtype, copy=False))
    return images, labels


def augment_batch(images: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Vectorized pad-4 → random crop → random flip.  dtype-preserving:
    pad/crop/flip move pixels without arithmetic, so uint8 in → uint8
    out, bit-identical to augmenting the same pixels in float32."""
    n = images.shape[0]
    padded = np.zeros((n, HEIGHT + 8, WIDTH + 8, NUM_CHANNELS),
                      images.dtype)
    padded[:, 4:4 + HEIGHT, 4:4 + WIDTH] = images
    ys = rng.integers(0, 9, n)
    xs = rng.integers(0, 9, n)
    flips = rng.random(n) < 0.5
    out = np.empty_like(images)
    for i in range(n):  # gather per-image offsets (cheap vs. the copy)
        crop = padded[i, ys[i]:ys[i] + HEIGHT, xs[i]:xs[i] + WIDTH]
        out[i] = crop[:, ::-1] if flips[i] else crop
    return out


def standardize(images: np.ndarray) -> np.ndarray:
    """tf.image.per_image_standardization: per-image zero mean, unit
    stddev with the 1/√N floor."""
    n_elems = float(np.prod(images.shape[1:]))
    mean = images.mean(axis=(1, 2, 3), keepdims=True)
    std = images.std(axis=(1, 2, 3), keepdims=True)
    adjusted = np.maximum(std, 1.0 / np.sqrt(n_elems))
    return (images - mean) / adjusted


def cifar_input_fn(data_dir: str, is_training: bool, batch_size: int,
                   seed: int = 0, process_id: Optional[int] = None,
                   process_count: Optional[int] = None,
                   drop_remainder: bool = True,
                   wire: str = "float32", start_step: int = 0) -> Iterator:
    """Yields (images, labels) numpy batches; infinite for training.

    POSITION-DERIVED randomness (crash-exact resume): the shuffle order
    of epoch *e* and the augmentation draws of batch *(e, k)* are each
    seeded from ``(seed, process_id, e[, k])`` counters, never from a
    long-lived RNG stream.  Batch *n* of the training stream is
    therefore a pure function of (seed, process, n) — a run restored
    from a checkpoint at step *n* passes ``start_step=n`` and sees the
    EXACT batch sequence the uninterrupted run would have seen, without
    replaying (or skipping) a single example.

    ``wire``: host→device batch format.  ``"float32"`` standardizes on
    the host (per_image_standardization, the r1-r3 behavior);
    ``"uint8"`` ships raw augmented pixels — 4x fewer bytes over the
    wire — and defers standardization to the compiled step
    (data/normalize.py cifar_standardize).  The augmentation
    (pad-crop-flip) moves pixels without arithmetic, so both wires see
    bit-identical pixel values.

    Multi-process: each process loads its shard of the files
    (cifar_preprocessing.py:147-152 semantics). `batch_size` is the
    per-host batch (global / process_count), matching how the loop's
    shard_batch assembles the global array.

    Eval with ``drop_remainder=False`` (the default config): examples
    are stride-sharded across processes and the final partial batch is
    zero-padded with a mask — batches are ``(images, labels, mask)``
    3-tuples, every process yields the same batch count, and eval
    covers exactly the full 10k test set once (the reference's full-set
    eval).  ``drop_remainder=True`` keeps the 2-tuple
    every-host-reads-everything behavior (benchmark purity).
    """
    import jax
    process_id = jax.process_index() if process_id is None else process_id
    process_count = (jax.process_count() if process_count is None
                     else process_count)
    if wire not in ("float32", "uint8"):
        raise ValueError(f"wire must be 'float32' or 'uint8', got {wire!r}")
    u8 = wire == "uint8"

    files = get_filenames(is_training, data_dir)
    if is_training and process_count > 1:
        files = shard_for_process(files, process_id, process_count) or files
    # raw uint8 resident set (150 MB, not 600); the f32 wire casts at
    # yield time, which reproduces the old all-f32 numerics exactly
    # (pad/crop/flip are value-preserving)
    images, labels = load_records(files, dtype=np.uint8)
    if is_training and len(images) < batch_size:
        raise ValueError(
            f"process {process_id}'s file shard holds {len(images)} images, "
            f"fewer than the per-host batch {batch_size}; reduce batch_size "
            f"or process count")
    # nonnegative per-process base entropy for the counter-derived RNGs
    seed_base = (int(seed) + 7919 * int(process_id)) & 0xFFFFFFFF

    def finalize(batch: np.ndarray) -> np.ndarray:
        if u8:
            return batch
        return standardize(batch.astype(np.float32))

    def gen():
        if is_training:
            per_epoch = len(images) // batch_size
            step = int(start_step)
            cur_epoch, order = -1, None
            while True:
                epoch, k = divmod(step, per_epoch)
                if epoch != cur_epoch:
                    # full-dataset shuffle, derived from (seed, epoch)
                    # alone — any step of any epoch is reconstructable
                    cur_epoch = epoch
                    order = np.random.default_rng(
                        np.random.SeedSequence(
                            [seed_base, epoch])).permutation(len(images))
                idx = order[k * batch_size:(k + 1) * batch_size]
                brng = np.random.default_rng(
                    np.random.SeedSequence([seed_base, epoch, k, 1]))
                yield finalize(augment_batch(images[idx], brng)), labels[idx]
                step += 1
        elif drop_remainder:
            for i in range(0, len(images) - batch_size + 1, batch_size):
                yield (finalize(images[i:i + batch_size].copy()),
                       labels[i:i + batch_size])
        else:
            # exact full-coverage eval: each process takes the stride
            # slice [pid::pcount]; all processes compute the same batch
            # count from the (globally known) total, so the collective
            # eval steps stay aligned
            total = len(images)
            local_idx = np.arange(process_id, total, process_count)
            max_local = -(-total // process_count)
            nbatches = -(-max_local // batch_size)
            for b in range(nbatches):
                sel = local_idx[b * batch_size:(b + 1) * batch_size]
                imgs = np.zeros((batch_size, HEIGHT, WIDTH, NUM_CHANNELS),
                                np.uint8 if u8 else np.float32)
                lbls = np.zeros((batch_size,), np.int32)
                mask = np.zeros((batch_size,), np.float32)
                if len(sel):
                    imgs[:len(sel)] = finalize(images[sel].copy())
                    lbls[:len(sel)] = labels[sel]
                    mask[:len(sel)] = 1.0
                yield imgs, lbls, mask

    return gen()
