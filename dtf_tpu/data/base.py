"""Dataset contract.

The reference's contract is `input_fn(is_training, data_dir, batch_size,
…, input_context) -> tf.data.Dataset` (SURVEY §1 L3).  Ours is the same
shape minus tf.data: an ``input_fn`` returns a Python iterator of
host-side numpy ``(images, labels)`` batches — infinite (repeating) for
training, one-pass for eval — plus a :class:`DatasetSpec` describing
cardinalities so the loop can do the reference's epoch math
(steps_per_epoch, eval steps, `steps // num_replicas` splits).

Per-process sharding follows the reference's shard-by-file rule
(cifar_preprocessing.py:147-152): each process reads a disjoint 1/N.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    name: str
    image_size: int
    num_channels: int
    num_classes: int       # for token datasets: the vocabulary size
    num_train: int
    num_eval: int
    one_hot: bool          # cifar uses one-hot + categorical CE; imagenet sparse
    mean_subtract: bool = False
    seq_len: int = 0       # >0 ⇒ token-sequence dataset ([B, S] int32 inputs,
                           # next-token labels); enables the 'seq' mesh axis

    @property
    def image_shape(self):
        return (self.image_size, self.image_size, self.num_channels)

    @property
    def is_sequence(self) -> bool:
        return self.seq_len > 0


# Cardinalities from the reference:
#   cifar: cifar_preprocessing.py NUM_IMAGES train 50_000 / validation 10_000
#   imagenet: imagenet_preprocessing.py:46-49 train 1_281_167 / validation 50_000,
#   1001 classes (label 0 = background, resnet_model num_classes=1001; sparse
#   labels shifted to [0,1000) in parse_record :254-255 — we keep 1001-way
#   logits with labels in [0,1001) after shift, matching the main's usage)
CIFAR10 = DatasetSpec("cifar10", 32, 3, 10, 50_000, 10_000, one_hot=True)
IMAGENET = DatasetSpec("imagenet", 224, 3, 1001, 1_281_167, 50_000,
                       one_hot=False, mean_subtract=True)
# Language-modeling workload (no reference equivalent — the reference is
# vision-only, SURVEY §5.7 — but long-context is first-class here):
# next-token prediction over [B, seq_len] int32 token ids.
LM = DatasetSpec("lm", 0, 0, num_classes=32_768, num_train=100_000,
                 num_eval=1_000, one_hot=False, seq_len=2048)

_SPECS = {"cifar10": CIFAR10, "cifar": CIFAR10, "imagenet": IMAGENET,
          "lm": LM}


def get_dataset_spec(name: str) -> DatasetSpec:
    if name not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; have {sorted(_SPECS)}")
    return _SPECS[name]
