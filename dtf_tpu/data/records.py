"""TFRecord container + tf.train.Example wire format, first-principles.

The reference reads TFRecordDataset shards and parses Example protos
with C++ tf.data kernels (imagenet_preprocessing.py:307-310, :156-223).
This module owns those formats natively — no TensorFlow, no protobuf
runtime — so the framework can read (and, for tests/tools, write) the
exact same files:

  TFRecord framing (per record):
      uint64 length (LE) | uint32 masked-crc32c(length) |
      bytes data[length] | uint32 masked-crc32c(data)
  masked_crc = ((crc >> 15 | crc << 17) + 0xa282ead8) & 0xffffffff,
  crc32c = Castagnoli polynomial 0x1EDC6F41 (reflected 0x82F63B78).

  tf.train.Example = { 1: Features { 1: map<string, Feature> } }
  Feature = oneof { 1: BytesList, 2: FloatList, 3: Int64List },
  each list = { 1: repeated value } (floats/ints may be packed).

A C++ implementation with the same contract lives in dtf_tpu/native
(used when built, ~10× faster); this file is the reference
implementation and fallback.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Union

import numpy as np

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), table-driven
# ---------------------------------------------------------------------------

def _make_crc_table():
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (0x82F63B78 * (c & 1))
        table.append(c)
    return table


_CRC_TABLE = _make_crc_table()


def crc32c(data: bytes) -> int:
    """Per-byte table loop — correctness reference; the native C++ path
    handles bulk throughput."""
    table = _CRC_TABLE
    c = 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ table[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# TFRecord framing
# ---------------------------------------------------------------------------

def read_tfrecord_file(path: str, verify_crc: bool = False) -> Iterator[bytes]:
    """Yields the raw serialized records of one TFRecord file.

    Dispatches to the C++ reader (dtf_tpu/native) when built; the pure
    Python below is the reference implementation and fallback."""
    try:
        from dtf_tpu import native
        dispatch = native.available()
    except Exception:  # unbuilt, unloadable (wrong arch), anything — fall back
        dispatch = False
    if dispatch:
        yield from native.read_tfrecord_file(path, verify_crc)
        return
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise IOError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            if verify_crc:
                (crc,) = struct.unpack("<I", header[8:12])
                if masked_crc32c(header[:8]) != crc:
                    raise IOError(f"{path}: corrupt length crc")
            data = f.read(length)
            if len(data) < length:
                raise IOError(f"{path}: truncated record body")
            footer = f.read(4)
            if len(footer) < 4:
                raise IOError(f"{path}: truncated record footer")
            if verify_crc:
                (crc,) = struct.unpack("<I", footer)
                if masked_crc32c(data) != crc:
                    raise IOError(f"{path}: corrupt data crc")
            yield data


# (path, mtime_ns, size) → record count: repeated evals re-count the
# same immutable shard files otherwise (one tiny seek+read per record —
# noticeable on high-latency network storage)
_COUNT_CACHE: dict = {}


def count_tfrecord_records(path: str) -> int:
    """Record count of one TFRecord file, skipping payloads via seek —
    O(records) tiny reads, no payload I/O, cached per (path, mtime,
    size).  Used by the exact-coverage eval to agree on the per-host
    batch count ahead of decoding."""
    import os
    st = os.stat(path)
    key = (path, st.st_mtime_ns, st.st_size)
    cached = _COUNT_CACHE.get(key)
    if cached is not None:
        return cached
    n = 0
    with open(path, "rb") as f:
        f.seek(0, 2)
        end = f.tell()
        pos = 0
        while pos < end:
            f.seek(pos)
            header = f.read(12)
            if len(header) < 12:
                raise IOError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            pos += 12 + length + 4
            if pos > end:
                raise IOError(f"{path}: truncated record body")
            n += 1
    _COUNT_CACHE[key] = n
    return n


def write_tfrecord_file(path: str, records) -> None:
    """Writes serialized records with valid framing (for tests/tools)."""
    with open(path, "wb") as f:
        for data in records:
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", masked_crc32c(header)))
            f.write(data)
            f.write(struct.pack("<I", masked_crc32c(data)))


# ---------------------------------------------------------------------------
# protobuf wire primitives
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = _read_varint(buf, pos)
    elif wire_type == 1:
        pos += 8
    elif wire_type == 2:
        ln, pos = _read_varint(buf, pos)
        pos += ln
    elif wire_type == 5:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    return pos


def _iter_fields(buf: bytes):
    pos, n = 0, len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            ln, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos:pos + ln]
            pos += ln
        elif wire == 0:
            val, pos = _read_varint(buf, pos)
            yield field, wire, val
        elif wire == 5:
            yield field, wire, buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            yield field, wire, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


FeatureValue = Union[List[bytes], np.ndarray]


def _parse_feature(buf: bytes) -> FeatureValue:
    """Feature = oneof { 1: BytesList, 2: FloatList, 3: Int64List }."""
    for field, _, payload in _iter_fields(buf):
        if field == 1:  # BytesList
            return [v for f, _, v in _iter_fields(payload) if f == 1]
        if field == 2:  # FloatList: packed (wire 2) or fixed32 (wire 5) —
            # both are little-endian f32 payloads
            floats = [np.frombuffer(v, dtype="<f4")
                      for f, _, v in _iter_fields(payload) if f == 1]
            return (np.concatenate(floats) if floats
                    else np.zeros((0,), np.float32))
        if field == 3:  # Int64List: packed or repeated varint
            ints: list = []
            for f, wire, v in _iter_fields(payload):
                if f != 1:
                    continue
                if wire == 2:  # packed varints
                    pos = 0
                    while pos < len(v):
                        val, pos = _read_varint(v, pos)
                        ints.append(val)
                else:
                    ints.append(v)
            return np.asarray(ints, dtype=np.int64)
    return []


def parse_example(serialized: bytes) -> Dict[str, FeatureValue]:
    """Parses a serialized tf.train.Example into {name: value}."""
    out: Dict[str, FeatureValue] = {}
    for field, _, features_buf in _iter_fields(serialized):
        if field != 1:
            continue
        for f, _, entry in _iter_fields(features_buf):
            if f != 1:
                continue
            key, feature = None, None
            for kf, _, kv in _iter_fields(entry):
                if kf == 1:
                    key = kv.decode("utf-8")
                elif kf == 2:
                    feature = kv
            if key is not None and feature is not None:
                out[key] = _parse_feature(feature)
    return out


# ---------------------------------------------------------------------------
# Example building (tests/tools)
# ---------------------------------------------------------------------------

def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def build_example(features: Dict[str, Union[bytes, List[bytes], List[int],
                                            List[float], np.ndarray]]) -> bytes:
    """Serializes {name: value} to a tf.train.Example (inverse of
    parse_example; used by tests and dataset-prep tools)."""
    entries = b""
    for key, value in features.items():
        if isinstance(value, bytes):
            value = [value]
        if isinstance(value, np.ndarray):
            value = value.tolist()
        if len(value) and isinstance(value[0], bytes):
            lst = b"".join(_len_delim(1, v) for v in value)
            feature = _len_delim(1, lst)
        elif len(value) and isinstance(value[0], float):
            packed = np.asarray(value, dtype="<f4").tobytes()
            feature = _len_delim(2, _len_delim(1, packed))
        else:
            packed = b"".join(_varint(int(v)) for v in value)
            feature = _len_delim(3, _len_delim(1, packed))
        entry = _len_delim(1, key.encode()) + _len_delim(2, feature)
        entries += _len_delim(1, entry)
    return _len_delim(1, entries)
