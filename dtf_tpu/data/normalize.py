"""On-chip input normalization — the compiled-step half of the uint8 wire.

TPU-first placement of the reference's normalization ops: the reference
runs mean subtraction / per-image standardization inside its C++ graph
runtime (imagenet_preprocessing.py:397-430, cifar_preprocessing.py:98);
the TPU-native home for that math is the chip.  Pipelines ship uint8
HWC batches — 4x fewer host→device bytes than a float32 wire, the
measured bottleneck of both r3 recorded runs (RUN_r03.json:
38 MB/batch ImageNet transfer-bound at 28.6 img/s) — and the dataset's
normalization runs in f32 as the FIRST op inside the jitted train/eval
step, where XLA fuses it into the consuming convolution's input.

Numerics: uint8→f32 conversion is exact, and these functions apply the
same f32 arithmetic the host pipelines apply, so on-chip normalization
of a uint8 batch matches host normalization of the same pixels (tests
pin this; reductions in per-image standardization may differ by float
association order, ~1e-6 relative).  The only wire-format delta is
ImageNet's post-resize round-half-up to uint8 (≤0.5/255 quantization of
bilinear samples — below JPEG decode noise).
"""

from __future__ import annotations

import jax.numpy as jnp


def cifar_standardize(images):
    """tf.image.per_image_standardization in-graph: per-image zero mean,
    unit stddev with the 1/sqrt(N) floor (cifar_preprocessing.py:98 —
    the host-side twin is data/cifar.py standardize)."""
    x = images.astype(jnp.float32)
    n_elems = float(x.shape[-1] * x.shape[-2] * x.shape[-3])
    mean = jnp.mean(x, axis=(-3, -2, -1), keepdims=True)
    std = jnp.std(x, axis=(-3, -2, -1), keepdims=True)
    adjusted = jnp.maximum(std, 1.0 / jnp.sqrt(jnp.float32(n_elems)))
    return (x - mean) / adjusted


def imagenet_mean_subtract(images):
    """Channel-mean subtraction without scaling
    (imagenet_preprocessing.py:397-430 — the host twin is
    data/imagenet.py CHANNEL_MEANS)."""
    from dtf_tpu.data.imagenet import CHANNEL_MEANS
    return images.astype(jnp.float32) - jnp.asarray(CHANNEL_MEANS)


def for_dataset(name: str):
    """The on-chip normalize fn a uint8-wire pipeline defers to."""
    fns = {"cifar10": cifar_standardize,
           "imagenet": imagenet_mean_subtract}
    if name not in fns:
        raise ValueError(f"no on-chip normalization for dataset {name!r}")
    return fns[name]


def for_config(cfg, spec):
    """The compiled-step normalization a config's input wire implies —
    the SINGLE source of that decision for every training path (SPMD
    runner and async PS).  None when batches arrive host-normalized:
    the float32 wire, or synthetic data (the same
    use_synthetic_data/data_dir predicate the input-fn builders branch
    on), or token-sequence datasets (no image normalization)."""
    if (cfg.input_wire != "uint8" or cfg.use_synthetic_data
            or not cfg.data_dir or spec.is_sequence):
        return None
    return for_dataset(spec.name)
