"""Synthetic-data smoke harness — `integration.run_synthetic` parity.

The reference's e2e tests all funnel through
``official.utils.testing.integration.run_synthetic(main, extra_flags)``
(reference resnet_cifar_test.py:73-77, SURVEY.md §3.6): parse the extra
flags, force the synthetic data backend, invoke the real ``run()``, and
treat "did not crash" as the assertion.  This module provides the same
contract against our flag system so downstream users' test suites port
directly.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from dtf_tpu.config import Config, parse_flags


def run_synthetic(main: Callable[[Config], dict],
                  extra_flags: Optional[Sequence[str]] = None,
                  synth: bool = True,
                  defaults: Optional[dict] = None) -> dict:
    """Run ``main`` (a ``run(cfg) -> stats`` callable) with synthetic
    data and the given extra CLI flags; returns the stats dict.

    Mirrors the reference helper: `-use_synthetic_data true` is forced
    (unless ``synth=False``), and checkpointing is disabled so smoke
    cells leave nothing behind.
    """
    argv = list(extra_flags or [])
    if synth:
        argv += ["--use_synthetic_data", "true"]
    base = dict(skip_checkpoint=True, model_dir="")
    base.update(defaults or {})
    cfg = parse_flags(argv, defaults=base)
    return main(cfg)
