"""Test-support helpers (the `official.utils.testing` equivalent)."""
