"""dtf_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of
PlusWayne/distributed-tensorflow (reference mounted at /root/reference):
distributed data-parallel training of ResNet-50 / ResNet-56 image
classifiers over device meshes, with synchronous (mirrored,
multi-worker-mirrored, horovod) and parameter-server-equivalent modes,
a tf.data-equivalent input pipeline (native C++ readers + host
prefetch), benchmark-grade observability, and checkpointing.

Layering (SURVEY.md §7):
  config    — typed run/topology configuration + CLI parsing
  runtime   — process/device initialization, mesh construction
  data      — input pipelines (synthetic, CIFAR-10 binary, ImageNet TFRecord)
  models    — ResNet-50 v1.5, ResNet-(6n+2) CIFAR family, trivial model
  train     — jitted SPMD train/eval loops, LR schedules, checkpointing
  parallel  — named distribution strategies over one SPMD core; sequence
              parallelism (ring attention) primitives
  ops       — Pallas TPU kernels for hot ops
  serve     — checkpoint→inference bridge, KV-cache decode, dynamic
              batching engine (the checkpoints' consumer)
  utils     — BenchmarkMetric logging, stats, profiler hooks
  cli       — entry points (cifar_main, imagenet_main, serve_main,
              launcher)
"""

__version__ = "0.1.0"


def _install_shard_map_shim() -> None:
    """jax-0.4.x compat: expose `jax.shard_map` with the modern keyword
    surface on installs that only ship `jax.experimental.shard_map`.

    The training stack calls `jax.shard_map(..., check_vma=...)` (the
    jax>=0.5 API).  jax 0.4.37 has no top-level `jax.shard_map` and its
    experimental function spells that keyword `check_rep` — without the
    shim every shard_map-backed training test dies in AttributeError at
    import-adjacent time (the ROADMAP-documented cause of the 109
    standing tier-1 failures).  Installed only when absent, so a real
    jax>=0.5 is untouched.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # pragma: no cover - no known jax lacks both
        return
    import functools

    @functools.wraps(_shard_map)
    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map(f, **kw)

    jax.shard_map = shard_map


_install_shard_map_shim()
