"""dtf_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of
PlusWayne/distributed-tensorflow (reference mounted at /root/reference):
distributed data-parallel training of ResNet-50 / ResNet-56 image
classifiers over device meshes, with synchronous (mirrored,
multi-worker-mirrored, horovod) and parameter-server-equivalent modes,
a tf.data-equivalent input pipeline (native C++ readers + host
prefetch), benchmark-grade observability, and checkpointing.

Layering (SURVEY.md §7):
  config    — typed run/topology configuration + CLI parsing
  runtime   — process/device initialization, mesh construction
  data      — input pipelines (synthetic, CIFAR-10 binary, ImageNet TFRecord)
  models    — ResNet-50 v1.5, ResNet-(6n+2) CIFAR family, trivial model
  train     — jitted SPMD train/eval loops, LR schedules, checkpointing
  parallel  — named distribution strategies over one SPMD core; sequence
              parallelism (ring attention) primitives
  ops       — Pallas TPU kernels for hot ops
  serve     — checkpoint→inference bridge, KV-cache decode, dynamic
              batching engine (the checkpoints' consumer)
  utils     — BenchmarkMetric logging, stats, profiler hooks
  cli       — entry points (cifar_main, imagenet_main, serve_main,
              launcher)
"""

__version__ = "0.1.0"
