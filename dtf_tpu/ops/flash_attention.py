"""Flash attention for TPU — Pallas forward kernel + blockwise backward.

Why a hand-written kernel when XLA fuses everything else (SURVEY.md
§2.4 — the reference's equivalent layer is cuDNN): naive attention
materializes the [S, S] score matrix in HBM, so at long context the op
is HBM-bound.  The Pallas kernel keeps each [block_q, block_k] score
tile in VMEM, carries the online-softmax state (ops.blockwise math) in
registers/VMEM, and only ever writes the [S, D] output — turning an
O(S²) HBM traffic op into O(S·D).

Grid: (batch·heads, Sq/block_q); each program streams K/V through VMEM
in block_k slices.  The backward has two formulations, both
recomputing probabilities per tile from the saved log-sum-exp (the
standard flash trade: extra FLOPs for O(S²) less HBM traffic):

- **fused** (`_dfused_kernel`, the default where its [Sq, D] f32 dq
  scratch fits VMEM — seq ≤ 4096 at d 128): dq, dk, dv from ONE
  traversal of the tile space — 5 tile matmuls and one softmax
  recompute per tile vs the split pair's 7 and two.  Measured r5,
  flagship step [16, 2048, 6, 128]: 235.2 → 218-223 ms (+5-8%
  tokens/s, mfu_model 0.561 → 0.59-0.605), isolated bwd 3.99 → 3.31 ms.
- **split** (`_dq_kernel` + `_dkdv_kernel`, longer sequences): dq
  streaming K/V; dk+dv streaming Q/dO — single writer per output
  tile, no atomics, VMEM capped at the block size regardless of
  sequence length: seq 32k compiles and runs (fwd 7.2 ms at
  [1, 32768, 4, 128]) where a resident-K/V formulation exceeds scoped
  VMEM from seq 8k.

`_blockwise_bwd` (plain JAX, same math) remains as the portable oracle
both are tested against (fused ≡ split ≡ oracle,
test_pallas_fused_bwd_matches_split).  Measured on one TPU v5 lite
chip, [2, 8192, 8, 128] bf16 causal (r4 sync-cancelled protocol, split
path): fwd ~2.5-3.0 ms, backward-only ~5.8-9.0 ms across sessions
(bench_lm.py --variant flash; bwd does 2.5× the forward's FLOPs; the
bwd dropped 25% when its kernels moved to f32-scratch accumulation
with native-dtype output stores).  All kernels stream their long-axis
operands through VMEM one block per sequential grid step — carries
live in VMEM scratch.

Causal masking is diagonal-only: blocks the diagonal never crosses run
a mask-free accumulate (no iota/compare/select per element), and only
straddling blocks pay the masking VPU work — measured ~10% off the
fwd kernel at [16, 2048, 6, 128].

The d_head-64 penalty (GPT-2's 12×64 layout runs ~2.2× slower f+b than
the flagship's 6×128 at identical parameters) is intrinsic MXU
geometry, not a kernel gap — matmul cost conserves output_tiles ×
ceil(contraction/128) passes under every head-packing construction,
and 2× heads means 2× softmax score elements.  `bench_lm.py --variant
dhead` is the committed reproducible measurement.

On non-TPU backends `flash_attention` transparently falls back to the
differentiable `ops.blockwise.blockwise_attention` (same math), so the
API is portable and testable on the CPU mesh.  Pass
``use_pallas="interpret"`` to force the kernel through the Pallas
interpreter on CPU (used by tests to validate the kernel itself).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dtf_tpu.ops import blockwise as bw

# 1024 measured fastest for the streaming kernel on v5e (block sweep
# at seq 8k: 1024² ≈ 10.5 ms vs 512² ≈ 16 ms — fewer grid steps, same
# capped VMEM; 2048-blocks exceed scoped VMEM and fail to compile).
# Re-swept r4 at the flagship step shape [16,2048,6,128] under the
# loop-differenced protocol (pre-scratch-store kernels — relative
# ordering is what the sweep establishes): 1024² f+b 5.40 ms vs
# 512×1024 6.11, 1024×512 6.43, 512² 7.19, 256×1024 7.63, 256² 15.3 —
# every compilable alternative loses 13-180%, confirming the default;
# a bwd-only sweep agreed (1024² 2.7 ms vs 512×1024 5.0, 1024×512
# 5.1).  Both sweeps predate the scratch-store kernels — the relative
# ordering, not the absolute times, is what they establish
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024

# base-2 softmax folding (bwd kernels): exp(x) lowers to
# exp2(x·log2 e), so folding log2 e into the score scale deletes one
# per-element VPU multiply from the recompute (measured neutral on
# v5e flagship shapes — see _dq_kernel)
_LOG2E = 1.4426950408889634


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, oacc_ref, m_ref,
                l_ref, *, scale, causal):
    """Grid (BH, Sq/block_q, Sk/block_k): one K/V block per step.

    K/V stream through VMEM one [block_k, D] tile at a time (the r2
    kernel held the FULL [Sk, D] K and V per program, which sat at the
    ~16 MB scoped-VMEM edge from seq 8k and failed outright beyond).
    The online-softmax carry (un-normalized o in f32, running max m,
    denominator l) lives in VMEM scratch that persists across the
    sequential k grid dimension — never touching HBM.  The final
    (o, lse) are written on the last live k step.

    Inputs stay in their native dtype (bf16 in production): the MXU
    multiplies bf16×bf16 with f32 accumulation at full rate, and for
    bf16 inputs the products are exact in f32 — upcasting first only
    slowed the matmuls (measured ~20 vs ~70 TFLOP/s on v5e).
    """
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    num_kv = pl.num_programs(2)
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        oacc_ref[...] = jnp.zeros_like(oacc_ref)
        m_ref[...] = jnp.full_like(m_ref, bw.NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = (jk * block_k <= (iq + 1) * block_q - 1) if causal else True
    # blocks entirely at-or-below the diagonal need no mask at all —
    # the per-element iota/compare/select VPU work only runs on blocks
    # the diagonal actually crosses
    straddles = (jk * block_k + block_k - 1 > iq * block_q) if causal \
        else False

    def _accumulate(bias):
        o, m, l = bw.block_accumulate(
            oacc_ref[...], m_ref[...][:, 0], l_ref[...][:, 0],
            q_ref[...], k_ref[...], v_ref[...], scale, bias)
        oacc_ref[...] = o
        m_ref[...] = m[:, None]
        l_ref[...] = l[:, None]

    @pl.when(live & jnp.logical_not(straddles) if causal else live)
    def _compute_unmasked():
        _accumulate(None)

    if causal:
        @pl.when(live & straddles)
        def _compute_masked():
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            _accumulate(jnp.where(q_pos >= k_pos, 0.0, bw.NEG_INF))

    if causal:
        j_last = jnp.minimum(
            num_kv - 1, jax.lax.div((iq + 1) * block_q - 1, block_k))
    else:
        j_last = num_kv - 1

    @pl.when(jk == j_last)
    def _finalize():
        o = oacc_ref[...]
        m = m_ref[...][:, 0]
        l = l_ref[...][:, 0]
        o_ref[...] = bw.finalize(o, l).astype(o_ref.dtype)
        lse = (jnp.maximum(m, bw.NEG_INF)
               + jnp.log(jnp.where(l == 0.0, 1.0, l)))
        lse_ref[...] = lse[:, None]  # [block_q, 1]; see out_specs note


def _pallas_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    """q, k, v: [BH, S, D] → (o [BH, Sq, D], lse [BH, Sq])."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q, sk // block_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse kept 3-D [BH, Sq, 1]: TPU lowering requires the last
            # two block dims to tile (8, 128) or equal the array dims;
            # (block_q, 1) satisfies that where a 1-D (block_q,) cannot.
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        # f32 online-softmax carry, on-chip only: persists across the
        # sequential k grid dimension, re-initialized at jk == 0
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    o, lse = out
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# Pallas backward kernels
#
# Two kernels, the standard flash-attention split:
#   dq:    grid (BH, Sq/block_q) — each program owns one dq tile and
#          streams K/V blocks (same traversal as the forward).
#   dk/dv: grid (BH, Sk/block_k) — each program owns one dk+dv tile and
#          streams Q/dO blocks.  No atomics, no cross-program
#          accumulation: every output tile has exactly one writer.
# Probabilities are recomputed from the saved LSE per tile in VMEM
# (the flash trade: O(S²) HBM traffic never happens).  delta =
# rowsum(dO·O) is a cheap [BH, Sq] contraction done in plain JAX.
# Under causal masking each program skips the dead triangle
# (dq: K blocks past the diagonal; dk/dv: Q blocks before it).
# ---------------------------------------------------------------------------

def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
              scale, masked, iq, jk, block_q, block_k):
    """One (q-block, k-block) tile of the flash backward recompute —
    the SINGLE copy of the numerics shared by the split dq, split
    dk/dv, and fused kernels (each applies its own accumulator updates
    to the returned tensors).  Native-dtype operands with f32
    accumulation (see _fwd_kernel); base-2 softmax recompute (see
    _dq_kernel's historical note: folding log2 e into the scale turns
    exp into a raw exp2 — lse arrives base-2 as lse3); diagonal-only
    masking.  Returns (p, do, q, k, ds)."""
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[...][:, 0]
    delta = delta_ref[...][:, 0]
    s2 = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32
                             ) * (scale * _LOG2E)
    if masked:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s2 = jnp.where(q_pos >= k_pos, s2, bw.NEG_INF)
    p = jnp.exp2(s2 - lse[:, None])   # [bq, bk]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
    return p, do, q, k, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dqacc_ref, *, scale, causal):
    """Grid (BH, Sq/block_q, Sk/block_k): K/V stream one block per step
    (same capped-VMEM pattern as the forward); the dq tile accumulates
    in f32 VMEM scratch across the sequential k dimension and stores
    once, in the output's native dtype, on the last step — a bf16
    output never materializes f32 gradients in HBM.  The previous form
    (f32 output refs + astype outside the kernel) moved ~0.9 GB/layer
    of extra gradient bytes; measured same-session A/B: flagship step
    238.6 → 231.9 ms (+2.9% tokens/s), micro bwd-only 7.8 → 5.8 ms at
    [2, 8192, 8, 128]."""
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dqacc_ref[...] = jnp.zeros_like(dqacc_ref)

    live = (jk * block_k <= (iq + 1) * block_q - 1) if causal else True
    # diagonal-only masking (see _fwd_kernel): blocks the diagonal does
    # not cross skip the per-element mask entirely
    straddles = (jk * block_k + block_k - 1 > iq * block_q) if causal \
        else False

    def _tile(masked):
        # the base-2 recompute historically lived here: folding
        # log2(e) into the scale the per-element multiply already pays
        # turns exp() (exp2 + a multiply) into a raw exp2 — the lse
        # conversion is per-ROW.  Strictly fewer VPU ops; measured
        # NEUTRAL end-to-end on v5e at the flagship shapes (the bwd is
        # not multiply-bound there) — kept because it can only help on
        # shapes/chips where the VPU is the constraint.  The numerics
        # are now single-sourced in _bwd_tile.
        _, _, _, k, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            scale=scale, masked=masked, iq=iq, jk=jk,
            block_q=block_q, block_k=block_k)
        dqacc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(live & jnp.logical_not(straddles) if causal else live)
    def _tile_unmasked():
        _tile(False)

    if causal:
        @pl.when(live & straddles)
        def _tile_masked():
            _tile(True)

    # unconditional (dead causal blocks still step the grid): the tile
    # is complete once the last k block has streamed past
    @pl.when(jk == pl.num_programs(2) - 1)
    def _store():
        dq_ref[...] = dqacc_ref[...].astype(dq_ref.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                 dv_ref, dkacc_ref, dvacc_ref, *, scale, causal, block_q,
                 block_k):
    """Grid (BH, Sk/block_k, Sq/block_q): the Pallas pipeline streams
    one [block_q] slice of Q/dO/lse/delta per step (never the full
    sequence in VMEM — the 2-D formulation VMEM-OOMed at seq 8k), and
    dk/dv accumulate in f32 VMEM scratch across the sequential q-grid
    dimension, storing native-dtype outputs once on the last step
    (see _dq_kernel)."""
    iq = pl.program_id(2)
    jk = pl.program_id(1)

    @pl.when(iq == 0)
    def _init():
        dkacc_ref[...] = jnp.zeros_like(dkacc_ref)
        dvacc_ref[...] = jnp.zeros_like(dvacc_ref)

    # causal: q blocks strictly above the diagonal contribute nothing
    live = ((iq + 1) * block_q - 1 >= jk * block_k) if causal else True
    # diagonal-only masking (see _fwd_kernel)
    straddles = (jk * block_k + block_k - 1 > iq * block_q) if causal \
        else False

    def _tile(masked):
        p, do, q, _, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            scale=scale, masked=masked, iq=iq, jk=jk,
            block_q=block_q, block_k=block_k)
        dvacc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dkacc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(live & jnp.logical_not(straddles) if causal else live)
    def _tile_unmasked():
        _tile(False)

    if causal:
        @pl.when(live & straddles)
        def _tile_masked():
            _tile(True)

    @pl.when(iq == pl.num_programs(2) - 1)
    def _store():
        dk_ref[...] = dkacc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dvacc_ref[...].astype(dv_ref.dtype)


def _dfused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dk_ref, dv_ref, dqacc_ref, dkacc_ref,
                   dvacc_ref, *, scale, causal, block_q, block_k):
    """Single-pass backward: dq, dk, dv from ONE traversal of the
    (q-block × k-block) tile space — the S and dP recomputes happen
    once per tile instead of once in each of the split kernels (5 tile
    matmuls vs the split pair's 7, and half the exp2 softmax-recompute
    VPU work).

    Grid (BH, Sk/block_k, Sq/block_q): dk/dv accumulate per k tile in
    block-sized f32 scratch across the inner q dimension (exactly the
    split _dkdv_kernel pattern); dq — whose accumulation runs across
    the OUTER k dimension, where block scratch can't carry it — lives
    in a FULL-SEQUENCE [Sq, D] f32 VMEM scratch, zeroed on the first k
    step and sliced per q tile.  That scratch is what bounds the
    kernel: Sq·D·4 bytes of VMEM (1 MB at the flagship 2048×128), so
    _pallas_backward gates the fused path on _FUSED_DQ_SCRATCH_MAX and
    falls back to the split kernels for longer sequences.  The dq
    output tile is written on EVERY visit with the current partial sum
    (defined value per flush; the sequentially-last flush carries the
    complete sum) — see the store-site comment."""
    iq = pl.program_id(2)
    jk = pl.program_id(1)
    num_q = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init_dq_slice():
        dqacc_ref[pl.dslice(iq * block_q, block_q), :] = jnp.zeros(
            (block_q, dqacc_ref.shape[1]), jnp.float32)

    @pl.when(iq == 0)
    def _init_dkdv():
        dkacc_ref[...] = jnp.zeros_like(dkacc_ref)
        dvacc_ref[...] = jnp.zeros_like(dvacc_ref)

    live = ((iq + 1) * block_q - 1 >= jk * block_k) if causal else True
    # diagonal-only masking (see _fwd_kernel)
    straddles = (jk * block_k + block_k - 1 > iq * block_q) if causal \
        else False

    def _tile(masked):
        p, do, q, k, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            scale=scale, masked=masked, iq=iq, jk=jk,
            block_q=block_q, block_k=block_k)
        dvacc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dkacc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dqacc_ref[pl.dslice(iq * block_q, block_q), :] += (
            jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32))

    @pl.when(live & jnp.logical_not(straddles) if causal else live)
    def _tile_unmasked():
        _tile(False)

    if causal:
        @pl.when(live & straddles)
        def _tile_masked():
            _tile(True)

    @pl.when(iq == num_q - 1)
    def _store_dkdv():
        dk_ref[...] = dkacc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dvacc_ref[...].astype(dv_ref.dtype)

    # dq tile iq is complete once the last k block has passed (under
    # causal masking contributions beyond the diagonal were dead).
    # The store is UNCONDITIONAL: the output block is revisited once
    # per outer k step, and Pallas may flush its VMEM buffer to HBM on
    # every revisit — writing the current partial sum each visit means
    # every flush carries a defined value and the final (sequentially
    # last) flush carries the complete one, instead of relying on
    # earlier flushes of an unwritten buffer being harmlessly
    # overwritten (r5 high-effort review; measured step-neutral).
    dq_ref[...] = dqacc_ref[
        pl.dslice(iq * block_q, block_q), :].astype(dq_ref.dtype)


# The fused kernel's [Sq, D] f32 dq scratch must fit VMEM next to the
# streamed tiles and the [block_q, block_k] score intermediates.
# The 2 MB gate (seq 4096 at d 128) is measured on both sides (r5):
# at seq 4096 the production step compiles and runs fused (128.3k
# tokens/s, mfu_model 0.603; jit-step, scan-wrapped grad-accum, and
# bare-call forms all verified on-chip — one micro-probe fori_loop
# harness hits a Mosaic compile failure there, a harness artifact, not
# a production path); at seq 8192 a forced fused arm (4 MB scratch,
# 512-q blocks) measures WORSE than the split kernels (isolated bwd
# 8.99 vs 8.66 ms) — the scratch squeezes the pipeline, so longer
# sequences keep the split streaming formulation.
_FUSED_DQ_SCRATCH_MAX = 2 * 1024 * 1024

# Fused-kernel q-block sweep, recorded because the obvious conclusion
# was wrong: ISOLATED loop-differenced bwd at [96, 2048, 128] measures
# 512×1024 at 1.74-1.81 ms vs 1024² at 2.54-3.31 (1024×512 4.71,
# 512² 2.98, 256×1024 3.17) — but the FULL flagship training step is
# block-q-neutral (2× runs each, same process: 147.0-147.2k tokens/s
# at 512 vs 147.2-147.6k at 1024).  The serialized micro loop amplifies
# pipeline-ramp effects the real step (bwd sandwiched between the
# block's matmuls, operands arriving from fusions) doesn't see.  The
# kernel therefore keeps the shared 1024² default — one fewer special
# case, chosen on the step-level evidence.


def _pallas_backward(q, k, v, o, lse, do, scale, causal, block_q, block_k,
                     interpret, fused=None):
    """All arrays [BH, S, D] (lse [BH, Sq]); returns (dq, dk, dv).

    ``fused``: None = auto (single-pass kernel when the [Sq, D] f32 dq
    scratch fits _FUSED_DQ_SCRATCH_MAX); True/False = force (tests pin
    both paths against each other and the oracle)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)               # [BH, Sq, 1]
    # pre-converted to base 2 for the kernels' exp2 softmax recompute
    # (the natural-log lse itself is the public residual contract)
    lse3 = lse[..., None] * _LOG2E

    if fused is None:
        fused = sq == sk and sq * d * 4 <= _FUSED_DQ_SCRATCH_MAX
    if fused:
        bq = block_q
        dq, dk, dv = pl.pallas_call(
            functools.partial(_dfused_kernel, scale=scale, causal=causal,
                              block_q=bq, block_k=block_k),
            grid=(bh, sk // block_k, sq // bq),
            in_specs=[
                pl.BlockSpec((None, bq, d), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((None, bq, d), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((None, bq, 1), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((None, bq, 1), lambda b, j, i: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, bq, d), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((sq, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, do, lse3, delta)
        return dq, dk, dv

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal),
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda b, i, j: (b, i, 0)),
        # native output dtype: accumulation lives in the f32 scratch,
        # so a bf16 dq never round-trips f32 gradients through HBM
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse3, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Blockwise backward (plain JAX, O(S·block) memory) — portable oracle
# ---------------------------------------------------------------------------

def _blockwise_bwd(q, k, v, o, lse, do, scale, causal, block_k):
    """Standard flash-attention backward, scanning K/V blocks.

    All arrays [BH, S, D] (lse [BH, Sq]) in float32.
    """
    sq, sk = q.shape[1], k.shape[1]
    num_blocks = sk // block_k
    delta = jnp.sum(do * o, axis=-1)                      # [BH, Sq]
    q_pos = jnp.arange(sq)

    kb = jnp.moveaxis(k.reshape(-1, num_blocks, block_k, k.shape[-1]), 1, 0)
    vb = jnp.moveaxis(v.reshape(-1, num_blocks, block_k, v.shape[-1]), 1, 0)

    def body(carry, blk):
        dq, j = carry
        kblk, vblk = blk                                   # [BH, bk, D]
        s = jnp.einsum("bqd,bkd->bqk", q, kblk) * scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)
            s = s + bw.causal_bias(q_pos, k_pos)
        p = jnp.exp(s - lse[..., None])                    # [BH, Sq, bk]
        dv = jnp.einsum("bqk,bqd->bkd", p, do)
        dp = jnp.einsum("bqd,bkd->bqk", do, vblk)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kblk)
        dk = jnp.einsum("bqk,bqd->bkd", ds, q)
        return (dq, j + 1), (dk, dv)

    (dq, _), (dk_b, dv_b) = jax.lax.scan(
        body, (jnp.zeros_like(q), jnp.int32(0)), (kb, vb))
    dk = jnp.moveaxis(dk_b, 0, 1).reshape(k.shape)
    dv = jnp.moveaxis(dv_b, 0, 1).reshape(v.shape)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing + public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret,
           fused=None):
    o, _ = _pallas_forward(q, k, v, scale, causal, block_q, block_k,
                           interpret)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               fused=None):
    o, lse = _pallas_forward(q, k, v, scale, causal, block_q, block_k,
                             interpret)
    # named for selective remat (models/transformer.py remat_policy
    # "dots"): the backward needs these residuals, and without the tags
    # a policy that saves only dot_generals would re-run this whole
    # forward kernel inside the backward pass (q/k/v recompute from the
    # saved qkv projection for free; o/lse are the expensive part)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, fused, res, do):
    q, k, v, o, lse = res
    # already native-dtype: the kernels accumulate in f32 scratch and
    # store in the inputs' dtypes
    return _pallas_backward(q, k, v, o, lse, do, scale, causal,
                            block_q, block_k, interpret, fused=fused)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    use_pallas=None, fused_bwd=None):
    """Multi-head attention, flash-style.  q, k, v: [B, S, H, D].

    ``block_q``/``block_k``: None = auto (the measured-fastest default,
    shrunk via gcd to divide the sequence — any seq length that worked
    before keeps working); explicit values must divide the sequence.

    ``use_pallas``: None = auto (Pallas on TPU, blockwise-JAX
    elsewhere); True/False = force; "interpret" = Pallas interpreter
    (CPU kernel validation).

    ``fused_bwd``: None = auto (single-pass backward kernel when its
    [Sq, D] dq scratch fits VMEM — see _dfused_kernel); True/False =
    force (benches A/B the two formulations).
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    scale = float(scale) if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if not use_pallas:
        bk = block_k if block_k is not None else math.gcd(
            DEFAULT_BLOCK_K, k.shape[1])
        return bw.blockwise_attention(q, k, v, causal=causal, scale=scale,
                                      block_k=bk)

    interpret = use_pallas == "interpret"
    b, sq, h, d = q.shape
    sk = k.shape[1]
    auto_q = block_q is None
    auto_k = block_k is None
    if block_q is None:
        block_q = math.gcd(DEFAULT_BLOCK_Q, sq)
    if block_k is None:
        block_k = math.gcd(DEFAULT_BLOCK_K, sk)
    block_q = max(min(block_q, sq), 1)
    block_k = max(min(block_k, sk), 1)
    # Odd seq lengths (not a multiple of 8) gcd-shrink below the TPU
    # (8, 128) tile minimum.  A block equal to the full array dim is
    # the one sub-8 shape Mosaic accepts (block == array dims), so
    # auto-selection falls back to a single whole-sequence block —
    # bounded by the scores-tile VMEM budget below; larger odd lengths
    # raise with the pad advice.
    _SCORES_ELEMS_MAX = 2 * 1024 * 1024  # 8 MB f32 of ~16 MB VMEM
    if not interpret:
        if auto_q and block_q < 8 and sq * block_k <= _SCORES_ELEMS_MAX:
            block_q = sq
        if auto_k and block_k < 8 and block_q * sk <= _SCORES_ELEMS_MAX:
            block_k = sk
    sub8_ok = lambda bq, bk: (bq >= 8 or bq == sq) and (bk >= 8 or bk == sk)
    if not interpret and not sub8_ok(block_q, block_k):
        # DEFAULT blocks are powers of two, so the gcd auto-shrink
        # lands on a power of two: anything below 8 violates the TPU
        # (8, 128) tile rule (unless block == array dim) and would die
        # opaquely in Mosaic lowering
        raise ValueError(
            f"auto block sizes ({block_q}, {block_k}) fell below the "
            f"TPU tile minimum of 8 for seq lengths ({sq}, {sk}); pad "
            f"the sequence to a multiple of 8 or pass explicit "
            f"block_q/block_k")
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"block sizes ({block_q}, {block_k}) must divide the seq "
            f"lengths ({sq}, {sk})")

    def merge(x):  # [B, S, H, D] → [B·H, S, D]
        return jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)

    o = _flash(merge(q), merge(k), merge(v), scale, causal, block_q,
               block_k, interpret, fused_bwd)
    return jnp.swapaxes(o.reshape(b, h, sq, d), 1, 2)
