"""TPU kernel library (Pallas) and blockwise attention math.

The reference's hot ops are third-party CUDA kernels (cuDNN conv/BN,
SURVEY.md §2.4).  On TPU, XLA already emits MXU-tiled convolutions, so
the kernel effort goes where XLA needs help: attention — materializing
the [S, S] score matrix is the HBM-bandwidth trap that flash/blockwise
attention avoids.
"""

from dtf_tpu.ops.blockwise import (NEG_INF, block_accumulate,
                                   blockwise_attention, mha_reference)
from dtf_tpu.ops.flash_attention import flash_attention
from dtf_tpu.ops.paged_attention import (cached_attention, gather_pages,
                                         paged_attention, write_pages)

__all__ = [
    "NEG_INF",
    "block_accumulate",
    "blockwise_attention",
    "mha_reference",
    "flash_attention",
    "cached_attention",
    "gather_pages",
    "paged_attention",
    "write_pages",
]
