"""Online-softmax blockwise attention — the shared math core.

One accumulation rule serves three consumers:
  - `ops.flash_attention` (Pallas TPU kernel + plain-JAX fallback),
  - `parallel.ring_attention` (the same rule where "blocks" are the
    K/V shards rotating around the 'seq' mesh axis via ppermute),
  - tests (against `mha_reference`).

The rule (Milakov & Gimelshein online softmax, as used by
flash/blockwise/ring attention): carry running row-max ``m``, running
denominator ``l`` and un-normalized output ``o`` across K/V blocks;
each block rescales the carry by ``exp(m_old - m_new)``.  Masked
positions contribute additive ``NEG_INF`` bias, never a post-hoc
where — so fully-masked blocks are numerically inert.

Internal layout is [batch, heads, seq, head_dim] ("BHSD"): the
einsums then contract over the minor-most dims, which XLA maps
straight onto the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Large-but-finite mask bias: keeps exp() exactly 0 for masked entries
# while avoiding the -inf - -inf = nan trap when an entire row of a
# block is masked.
NEG_INF = -1e30


def block_accumulate(o, m, l, q, k, v, scale: float, bias=None):
    """Fold one K/V block into the (o, m, l) carry.

    Shapes (BHSD layout):
      q [.., Sq, D]   k, v [.., Sk, D]
      o [.., Sq, D]   m, l [.., Sq]
      bias broadcastable to [.., Sq, Sk] (additive, NEG_INF = masked)

    Returns the updated (o, m, l).  ``o`` stays un-normalized; divide by
    ``l`` after the last block.
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # m_new can be NEG_INF only while every block so far was fully
    # masked; clamp the subtrahend so exp() sees finite arguments.
    m_safe = jnp.maximum(m_new, NEG_INF)
    p = jnp.exp(s - m_safe[..., None])
    corr = jnp.exp(m - m_safe)
    l_new = l * corr + jnp.sum(p, axis=-1)
    # P·V runs at the operands' native precision with f32 accumulation:
    # when v is bf16 (the TPU kernel path), p is cast DOWN so the MXU
    # sees bf16×bf16 (full rate) — the standard flash-attention trade.
    # f32 callers (oracle, ring attention) are bit-for-bit unchanged.
    o_new = o * corr[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return o_new, m_new, l_new


def finalize(o, l):
    """Normalize the accumulated output; fully-masked rows become 0."""
    denom = jnp.where(l == 0.0, 1.0, l)
    return o / denom[..., None]


def causal_bias(q_pos, k_pos):
    """Additive causal mask from absolute positions.

    q_pos [Sq], k_pos [Sk] → [Sq, Sk] with 0 where k may be attended
    (k_pos <= q_pos) and NEG_INF elsewhere.
    """
    return jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, NEG_INF)


def _to_bhsd(x):
    return jnp.swapaxes(x, -3, -2)


def mha_reference(q, k, v, *, causal: bool = False,
                  scale: Optional[float] = None):
    """Plain O(S²)-memory attention, the numerical ground truth.

    q, k, v: [batch, seq, heads, head_dim]; returns same shape/dtype
    as q's compute in float32 then cast back.
    """
    orig_dtype = q.dtype
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    qt, kt, vt = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    s = jnp.einsum("...qd,...kd->...qk", qt.astype(jnp.float32),
                   kt.astype(jnp.float32)) * scale
    if causal:
        s = s + causal_bias(jnp.arange(q.shape[-3]), jnp.arange(k.shape[-3]))
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("...qk,...kd->...qd", p, vt.astype(jnp.float32))
    return _to_bhsd(out).astype(orig_dtype)


def blockwise_attention(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None,
                        block_k: int = 512,
                        q_offset=0, k_offset=0):
    """Memory-efficient attention: scans K/V in blocks of ``block_k``.

    q, k, v: [batch, seq, heads, head_dim].  ``q_offset``/``k_offset``
    are the absolute positions of q[.., 0, ..] and k[.., 0, ..] — this
    is what lets ring attention reuse the function on rotating shards
    whose global position differs from their local index.  Offsets may
    be traced scalars.

    Differentiable (the scan is reverse-mode differentiable; memory is
    O(S·block_k) forward, with block K/V saved per step for the
    backward pass).
    """
    orig_dtype = q.dtype
    sq, sk = q.shape[-3], k.shape[-3]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    block_k = min(block_k, sk)
    num_blocks, rem = divmod(sk, block_k)
    if rem:
        raise ValueError(f"kv length {sk} not divisible by block_k {block_k}")

    qt = _to_bhsd(q).astype(jnp.float32)
    kt = _to_bhsd(k).astype(jnp.float32)
    vt = _to_bhsd(v).astype(jnp.float32)
    # stack K/V blocks on a leading scan axis
    kb = kt.reshape(*kt.shape[:-2], num_blocks, block_k, kt.shape[-1])
    kb = jnp.moveaxis(kb, -3, 0)
    vb = vt.reshape(*vt.shape[:-2], num_blocks, block_k, vt.shape[-1])
    vb = jnp.moveaxis(vb, -3, 0)

    q_pos = q_offset + jnp.arange(sq)
    o0 = jnp.zeros_like(qt)
    m0 = jnp.full(qt.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(qt.shape[:-1], jnp.float32)

    def body(carry, blk):
        o, m, l, i = carry
        kblk, vblk = blk
        bias = None
        if causal:
            k_pos = k_offset + i * block_k + jnp.arange(block_k)
            bias = causal_bias(q_pos, k_pos)
        o, m, l = block_accumulate(o, m, l, qt, kblk, vblk, scale, bias)
        return (o, m, l, i + 1), None

    (o, m, l, _), _ = jax.lax.scan(body, (o0, m0, l0, jnp.int32(0)), (kb, vb))
    return _to_bhsd(finalize(o, l)).astype(orig_dtype)
