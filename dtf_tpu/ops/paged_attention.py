"""Paged KV-cache primitives: page-pool writes, block-table gathers,
and gather-attention for serving decode.

The contiguous serving cache (one [num_slots, max_seq_len, H, Dh] slab
per layer) reserves worst-case HBM for every slot: a 4-token request
holds the same memory as a max-length one.  The paged layout is the
vLLM/PagedAttention discipline adapted to fixed-shape XLA:

  page pool    — one [num_pages, page_size, H, Dh] array per layer per
                 K/V, shared by every slot.  Token at logical position
                 ``p`` of a slot lives at pool row
                 ``block_table[slot, p // page_size]``, offset
                 ``p % page_size``.
  block table  — [B, max_pages_per_slot] int32 page ids, maintained
                 host-side by the serving engine's allocator.  Entries
                 for unallocated tail pages are 0 — see the scratch-page
                 invariant below.
  scratch page — pool page 0 is never handed to a request.  Inactive
                 rows of a fixed-shape decode batch still execute the
                 write (XLA has no dynamic batch), and their garbage
                 must land somewhere that no live sequence reads:
                 the engine passes an all-zeros block-table row for
                 such rows, steering both the write and the (ignored)
                 gather at page 0.

Everything here is shape-static: the gather always materializes the
full ``max_pages_per_slot * page_size`` logical window and masks, so
the decode step compiles exactly once regardless of pool occupancy.

``cached_attention`` (dense attention against a fixed-capacity KV
window, f32 softmax) also lives here — it is the shared score/softmax
math for both the contiguous cache path (models/transformer.py) and
the paged gather path.

Two formulations of attention-over-pages coexist:

  gather (``paged_attention``)      — materialize the gathered window,
      mask, dense softmax.  Portable, the CPU-default oracle.  Pays the
      PR-3 gather tax (~3% of contiguous step time) plus, for prefill
      chunks, a host-side STATIC window trim (one compile per window).
  kernel (``paged_flash_decode``)   — a Pallas kernel that reads KV
      pages THROUGH the block table in-kernel (scalar-prefetched, so
      each page's DMA source address is computed before the body runs):
      no gathered window ever materializes, and the window trim is
      FUSED — pages past ``index + S − 1`` are skipped by a dynamic
      ``pl.when`` predicate, so one compile covers every chunk index
      where the gather path needed one per static window.  Online-
      softmax carry in VMEM scratch (ops.blockwise math, the same rule
      the flash kernels use).

``paged_attention_auto`` dispatches between them: the kernel by default
on TPU, the gather oracle elsewhere; ``use_pallas="interpret"`` runs
the kernel through the Pallas interpreter on CPU (how tier-1 pins
kernel ≡ oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dtf_tpu.ops import blockwise as bw


def cached_attention(q, k, v, mask):
    """Dense attention against a fixed-size KV window.

    q [B, S, H, Dh] (S = the chunk being decoded), k/v [B, L, H, Dh]
    (L = the window capacity), mask [B, S, L] True where the query may
    attend.  Scores/softmax run in f32 (the flash kernels' accumulator
    precision); masked positions get a large negative score, and the
    output is cast back to q's dtype.  At decode shapes (S small, L
    fixed) the [S, L] score tile is small — no flash kernel needed."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return o.astype(q.dtype)


def write_pages(pool, new, block_table, index, page_aligned: bool = False):
    """Scatter a [B, S, H, Dh] chunk of K or V into the page pool.

    ``pool`` [P, page_size, H, Dh]; ``block_table`` [B, M] int32 page
    ids; ``index`` [B] int32 — the chunk's starting logical position
    per row (token i of row b lands at logical position index[b] + i).

    ``page_aligned`` (static) promises index % page_size == 0 and
    S % page_size == 0 for every row — the prefill-chunk case by
    engine construction.  The write then scatters WHOLE pages
    (S/page_size contiguous [page_size, H, Dh] blocks per row) instead
    of S individual token rows: XLA lowers the page-granular scatter to
    block memcpys where the token-granular form degenerates to
    row-at-a-time copies.  Decode steps (S = 1, arbitrary offset) take
    the token path.

    Positions past the block table's logical capacity (M * page_size)
    are clamped to the last logical slot; the engine's invariants make
    such writes garbage-onto-garbage (a padded prefill tail), never a
    live-token overwrite that the mask could later admit unwritten.
    Rows whose block-table entries are all 0 write into the scratch
    page (see module docstring)."""
    num_pages, page_size, h, dh = pool.shape
    b, s = new.shape[:2]
    capacity = block_table.shape[1] * page_size
    if page_aligned:
        n_pages = s // page_size
        pstart = index // page_size                          # [B]
        pidx = jnp.minimum(
            pstart[:, None] + jnp.arange(n_pages, dtype=jnp.int32)[None, :],
            block_table.shape[1] - 1)
        page = jnp.take_along_axis(block_table, pidx, axis=1)  # [B, n]
        return pool.at[page.reshape(-1)].set(
            new.reshape(b * n_pages, page_size, h, dh))
    pos = index[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    pos = jnp.minimum(pos, capacity - 1)                     # [B, S]
    page = jnp.take_along_axis(block_table, pos // page_size, axis=1)
    flat = page * page_size + pos % page_size                # [B, S]
    pool_flat = pool.reshape(num_pages * page_size, h, dh)
    pool_flat = pool_flat.at[flat.reshape(-1)].set(
        new.reshape(b * s, h, dh))
    return pool_flat.reshape(pool.shape)


def gather_pages(pool, block_table):
    """Gather each row's full logical KV window from the pool.

    ``pool`` [P, page_size, H, Dh], ``block_table`` [B, M] →
    [B, M * page_size, H, Dh], ordered by logical position (page 0 of
    the row first).  PAGE-granular: the gather moves M whole
    [page_size, H, Dh] blocks per row (contiguous memcpys under XLA),
    never individual tokens.  Unallocated entries gather the scratch
    page — callers mask those positions out (they are always ≥ the
    row's current length)."""
    num_pages, page_size, h, dh = pool.shape
    b, m = block_table.shape
    return pool[block_table].reshape(b, m * page_size, h, dh)


def paged_attention(q, pool_k, pool_v, block_table, index):
    """Attention of a chunk of queries over a slot's paged KV history.

    q [B, S, H, Dh] — S new queries per row, the row's global positions
    being ``index[b] + i``; pool_k/pool_v [P, page_size, H, Dh];
    block_table [B, M]; index [B] int32.  The chunk's own K/V must
    already be written into the pool (write-then-attend, exactly the
    contiguous cache path's ordering), so query i sees logical
    positions j <= index + i: the just-written chunk causally, the
    prefix fully, and never the unwritten tail (masked)."""
    k = gather_pages(pool_k, block_table)   # [B, L, H, Dh]
    v = gather_pages(pool_v, block_table)
    s = q.shape[1]
    capacity = k.shape[1]
    jpos = jnp.arange(capacity, dtype=jnp.int32)[None, None, :]
    qpos = (index[:, None, None]
            + jnp.arange(s, dtype=jnp.int32)[None, :, None])
    return cached_attention(q, k, v, jpos <= qpos)


# ---------------------------------------------------------------------------
# Pallas paged flash-decode kernel
# ---------------------------------------------------------------------------

def _paged_decode_kernel(tbl_ref, idx_ref, q_ref, k_ref, v_ref, o_ref,
                         oacc_ref, m_ref, l_ref, *, scale, page_size):
    """Grid (B, H, M): one (row, head) pair streams its pages.

    ``tbl_ref`` [B, M] and ``idx_ref`` [B] are scalar-prefetched: the
    pool in_specs' index maps read ``tbl_ref[b, j]`` to pick the DMA
    source page BEFORE the body runs — the gather never exists as an
    array.  The online-softmax carry (un-normalized o in f32, running
    max m, denominator l — ops.blockwise math, shared with the flash
    kernels) lives in VMEM scratch across the sequential page
    dimension.  Pages whose first position lies past ``index + S − 1``
    are skipped by a DYNAMIC predicate — the window trim the gather
    path did with a static slice, fused, so one compile covers every
    chunk index.  Within a live page the causal mask is positional:
    key position ``j·page + t`` is admitted iff ≤ ``index + i`` (the
    query's global position) — exactly the gather oracle's mask."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    s = q_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        oacc_ref[...] = jnp.zeros_like(oacc_ref)
        m_ref[...] = jnp.full_like(m_ref, bw.NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    idx = idx_ref[b]
    live = j * page_size <= idx + s - 1

    @pl.when(live)
    def _accumulate():
        kpos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        qpos = idx + jax.lax.broadcasted_iota(jnp.int32, (s, 1), 0)
        bias = jnp.where(kpos <= qpos, 0.0, bw.NEG_INF)
        o, m, l = bw.block_accumulate(
            oacc_ref[...], m_ref[...][:, 0], l_ref[...][:, 0],
            q_ref[...], k_ref[...], v_ref[...], scale, bias)
        oacc_ref[...] = o
        m_ref[...] = m[:, None]
        l_ref[...] = l[:, None]

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[...] = bw.finalize(
            oacc_ref[...], l_ref[...][:, 0]).astype(o_ref.dtype)


def paged_flash_decode(q, pool_k, pool_v, block_table, index, *,
                       scale=None, interpret: bool = False):
    """Attention of a chunk of queries over a slot's paged KV history,
    reading pages through the block table IN-KERNEL.

    Same contract as :func:`paged_attention` (write-then-attend; q
    [B, S, H, Dh], pools [P, page_size, H, Dh], block_table [B, M],
    index [B] int32) — the kernel is the hardware-speed formulation:
    no materialized gathered window, fused window trim (dead pages
    skipped dynamically), one compile per chunk SHAPE instead of one
    per static window."""
    b, s, h, d = q.shape
    page_size = pool_k.shape[1]
    m_pages = block_table.shape[1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    qh = jnp.swapaxes(q, 1, 2)                       # [B, H, S, D]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, m_pages),
        in_specs=[
            pl.BlockSpec((None, None, s, d),
                         lambda b_, h_, j, tbl, idx: (b_, h_, 0, 0)),
            pl.BlockSpec((None, page_size, None, d),
                         lambda b_, h_, j, tbl, idx: (tbl[b_, j], 0, h_, 0)),
            pl.BlockSpec((None, page_size, None, d),
                         lambda b_, h_, j, tbl, idx: (tbl[b_, j], 0, h_, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, s, d),
                               lambda b_, h_, j, tbl, idx: (b_, h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((s, d), jnp.float32),
            pltpu.VMEM((s, 1), jnp.float32),
            pltpu.VMEM((s, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale,
                          page_size=page_size),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=interpret,
    )(jnp.asarray(block_table, jnp.int32), jnp.asarray(index, jnp.int32),
      qh, pool_k, pool_v)
    return jnp.swapaxes(out, 1, 2)


def paged_flash_decode_reference(q, pool_k, pool_v, block_table, index, *,
                                 scale=None):
    """Plain-JAX page-by-page accumulation — the kernel's portable
    oracle, the same role ops.blockwise plays for the flash kernels:
    identical math (bw.block_accumulate per page, sequential page
    order).  Dead pages are accumulated under a fully-masked bias
    rather than skipped — numerically inert by the NEG_INF
    construction (p underflows to exactly 0, corr is exactly 1) — so
    the only divergence from the kernel is XLA's batched-vs-per-
    program einsum reduction order: float-ulp level, pinned by the
    tests at 1e-6 alongside argmax equality."""
    b, s, h, d = q.shape
    page_size = pool_k.shape[1]
    m_pages = block_table.shape[1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    qh = jnp.swapaxes(q, 1, 2)                       # [B, H, S, D]
    o = jnp.zeros(qh.shape, jnp.float32)
    m = jnp.full((b, h, s), bw.NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s), jnp.float32)
    qpos = index[:, None, None, None] + jnp.arange(
        s, dtype=jnp.int32)[None, None, :, None]     # [B, 1, S, 1]
    for j in range(m_pages):
        k = jnp.swapaxes(pool_k[block_table[:, j]], 1, 2)  # [B, H, P, D]
        v = jnp.swapaxes(pool_v[block_table[:, j]], 1, 2)
        kpos = (j * page_size + jnp.arange(page_size, dtype=jnp.int32)
                )[None, None, None, :]               # [1, 1, 1, P]
        bias = jnp.where(kpos <= qpos, 0.0, bw.NEG_INF)
        o, m, l = bw.block_accumulate(o, m, l, qh, k, v, scale, bias)
    return jnp.swapaxes(bw.finalize(o, l).astype(q.dtype), 1, 2)


def paged_attention_auto(q, pool_k, pool_v, block_table, index, *,
                         window_pages=None, use_pallas=None):
    """Dispatch between the kernel and the gather oracle.

    ``use_pallas``: None = auto (kernel on TPU — the default-on flag —
    gather elsewhere); True = kernel; "interpret" = kernel through the
    Pallas interpreter (CPU kernel validation); False = gather.
    ``window_pages`` (static) trims the GATHER path's window exactly as
    before; the kernel ignores it — its dynamic live predicate skips
    the same pages without a per-window recompile."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        return paged_flash_decode(q, pool_k, pool_v, block_table, index,
                                  interpret=use_pallas == "interpret")
    table = (block_table if window_pages is None
             else block_table[:, :window_pages])
    return paged_attention(q, pool_k, pool_v, table, index)
