"""Paged KV-cache primitives: page-pool writes, block-table gathers,
and gather-attention for serving decode.

The contiguous serving cache (one [num_slots, max_seq_len, H, Dh] slab
per layer) reserves worst-case HBM for every slot: a 4-token request
holds the same memory as a max-length one.  The paged layout is the
vLLM/PagedAttention discipline adapted to fixed-shape XLA:

  page pool    — one [num_pages, page_size, H, Dh] array per layer per
                 K/V, shared by every slot.  Token at logical position
                 ``p`` of a slot lives at pool row
                 ``block_table[slot, p // page_size]``, offset
                 ``p % page_size``.
  block table  — [B, max_pages_per_slot] int32 page ids, maintained
                 host-side by the serving engine's allocator.  Entries
                 for unallocated tail pages are 0 — see the scratch-page
                 invariant below.
  scratch page — pool page 0 is never handed to a request.  Inactive
                 rows of a fixed-shape decode batch still execute the
                 write (XLA has no dynamic batch), and their garbage
                 must land somewhere that no live sequence reads:
                 the engine passes an all-zeros block-table row for
                 such rows, steering both the write and the (ignored)
                 gather at page 0.

Everything here is shape-static: the gather always materializes the
full ``max_pages_per_slot * page_size`` logical window and masks, so
the decode step compiles exactly once regardless of pool occupancy.

``cached_attention`` (dense attention against a fixed-capacity KV
window, f32 softmax) also lives here — it is the shared score/softmax
math for both the contiguous cache path (models/transformer.py) and
the paged gather path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cached_attention(q, k, v, mask):
    """Dense attention against a fixed-size KV window.

    q [B, S, H, Dh] (S = the chunk being decoded), k/v [B, L, H, Dh]
    (L = the window capacity), mask [B, S, L] True where the query may
    attend.  Scores/softmax run in f32 (the flash kernels' accumulator
    precision); masked positions get a large negative score, and the
    output is cast back to q's dtype.  At decode shapes (S small, L
    fixed) the [S, L] score tile is small — no flash kernel needed."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return o.astype(q.dtype)


def write_pages(pool, new, block_table, index, page_aligned: bool = False):
    """Scatter a [B, S, H, Dh] chunk of K or V into the page pool.

    ``pool`` [P, page_size, H, Dh]; ``block_table`` [B, M] int32 page
    ids; ``index`` [B] int32 — the chunk's starting logical position
    per row (token i of row b lands at logical position index[b] + i).

    ``page_aligned`` (static) promises index % page_size == 0 and
    S % page_size == 0 for every row — the prefill-chunk case by
    engine construction.  The write then scatters WHOLE pages
    (S/page_size contiguous [page_size, H, Dh] blocks per row) instead
    of S individual token rows: XLA lowers the page-granular scatter to
    block memcpys where the token-granular form degenerates to
    row-at-a-time copies.  Decode steps (S = 1, arbitrary offset) take
    the token path.

    Positions past the block table's logical capacity (M * page_size)
    are clamped to the last logical slot; the engine's invariants make
    such writes garbage-onto-garbage (a padded prefill tail), never a
    live-token overwrite that the mask could later admit unwritten.
    Rows whose block-table entries are all 0 write into the scratch
    page (see module docstring)."""
    num_pages, page_size, h, dh = pool.shape
    b, s = new.shape[:2]
    capacity = block_table.shape[1] * page_size
    if page_aligned:
        n_pages = s // page_size
        pstart = index // page_size                          # [B]
        pidx = jnp.minimum(
            pstart[:, None] + jnp.arange(n_pages, dtype=jnp.int32)[None, :],
            block_table.shape[1] - 1)
        page = jnp.take_along_axis(block_table, pidx, axis=1)  # [B, n]
        return pool.at[page.reshape(-1)].set(
            new.reshape(b * n_pages, page_size, h, dh))
    pos = index[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    pos = jnp.minimum(pos, capacity - 1)                     # [B, S]
    page = jnp.take_along_axis(block_table, pos // page_size, axis=1)
    flat = page * page_size + pos % page_size                # [B, S]
    pool_flat = pool.reshape(num_pages * page_size, h, dh)
    pool_flat = pool_flat.at[flat.reshape(-1)].set(
        new.reshape(b * s, h, dh))
    return pool_flat.reshape(pool.shape)


def gather_pages(pool, block_table):
    """Gather each row's full logical KV window from the pool.

    ``pool`` [P, page_size, H, Dh], ``block_table`` [B, M] →
    [B, M * page_size, H, Dh], ordered by logical position (page 0 of
    the row first).  PAGE-granular: the gather moves M whole
    [page_size, H, Dh] blocks per row (contiguous memcpys under XLA),
    never individual tokens.  Unallocated entries gather the scratch
    page — callers mask those positions out (they are always ≥ the
    row's current length)."""
    num_pages, page_size, h, dh = pool.shape
    b, m = block_table.shape
    return pool[block_table].reshape(b, m * page_size, h, dh)


def paged_attention(q, pool_k, pool_v, block_table, index):
    """Attention of a chunk of queries over a slot's paged KV history.

    q [B, S, H, Dh] — S new queries per row, the row's global positions
    being ``index[b] + i``; pool_k/pool_v [P, page_size, H, Dh];
    block_table [B, M]; index [B] int32.  The chunk's own K/V must
    already be written into the pool (write-then-attend, exactly the
    contiguous cache path's ordering), so query i sees logical
    positions j <= index + i: the just-written chunk causally, the
    prefix fully, and never the unwritten tail (masked)."""
    k = gather_pages(pool_k, block_table)   # [B, L, H, Dh]
    v = gather_pages(pool_v, block_table)
    s = q.shape[1]
    capacity = k.shape[1]
    jpos = jnp.arange(capacity, dtype=jnp.int32)[None, None, :]
    qpos = (index[:, None, None]
            + jnp.arange(s, dtype=jnp.int32)[None, :, None])
    return cached_attention(q, k, v, jpos <= qpos)
