"""Typed configuration + CLI flag system.

One config system covering both hyperparameters and cluster topology —
the unification SURVEY.md §5.6 calls for.  The reference splits this
between absl flags (`official.utils.flags.core` groups composed by
`common.define_keras_flags`, reference common.py:248-309) and the
`TF_CONFIG` env JSON / `--worker_hosts --task_index` pair
(reference resnet_imagenet_main.py:108-110, ps_server/*_ps_0.py:40-50).

Here everything is a single dataclass, every field is a CLI flag
(``--name value`` or ``-name value``, absl style), per-process identity
may come from env vars, and a ``TF_CONFIG``-format JSON is still
understood for drop-in parity.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

# Strategy names accepted by --distribution_strategy.  Mirrors the
# reference's set (SURVEY.md §2.2) plus the TPU-native mode that
# BASELINE.json's north star names.
STRATEGIES = (
    "off",
    "one_device",
    "mirrored",
    "multi_worker_mirrored",
    "horovod",
    "parameter_server",
    "tpu",
)

DTYPES = ("fp32", "float32", "bf16", "bfloat16", "fp16", "float16")


@dataclasses.dataclass
class Config:
    """Every knob of a run.  Field comments cite the reference flag they
    provide parity for."""

    # --- base (official.utils.flags.core define_base) ---
    data_dir: str = ""                  # --data_dir
    model_dir: str = "/tmp/dtf_tpu"     # --model_dir
    clean: bool = False                 # model_helpers.apply_clean (imagenet_main.py:275)
    batch_size: int = 128               # global batch size, --batch_size
    train_epochs: int = 182             # --train_epochs (cifar default, cifar_main.py:226-230)
    epochs_between_evals: int = 1       # --epochs_between_evals
    stop_threshold: Optional[float] = None  # --stop_threshold
    export_dir: str = ""                # --export_dir (SavedModel equiv: orbax export)

    # --- performance (define_performance) ---
    dtype: str = "fp32"                 # --dtype; bf16 is the TPU-native mixed mode
    # --loss_scale: a number (static scale) or "dynamic" (TF2
    # LossScaleOptimizer semantics); only meaningful for fp16 parity
    loss_scale: Optional[Any] = None
    enable_xla: bool = True             # --enable_xla: always-on under JAX  # dtflint: disable=flag-dead (declared reference-parity no-op: XLA is unconditional under jax)
    all_reduce_alg: Optional[str] = None  # --all_reduce_alg (cifar_main.py:104)  # dtflint: disable=flag-dead (declared reference-parity no-op: XLA picks the collective on TPU)
    num_packs: int = 1                  # --num_packs gradient packing  # dtflint: disable=flag-dead (declared reference-parity no-op: XLA fuses collectives)
    datasets_num_private_threads: Optional[int] = None  # input pipeline threads
    # JDCT_IFAST decode in the native train pipeline: ±1-2 LSB vs the
    # default ISLOW (augmentation-noise territory), measurably faster —
    # a throughput opt-in, never a default
    input_fast_dct: bool = False
    # DCT-space 1/2–1/8 scaled decode (libjpeg scale_denom) for train
    # crops >=2x the output size: skips most IDCT work on large crops.
    # Changes the downsampling filter chain (scaled decode + bilinear
    # vs pure bilinear) — another throughput opt-in, never a default
    input_scaled_decode: bool = False
    # Host→device batch wire for the real-data pipelines.  "uint8"
    # (default, TPU-native): raw pixels over the wire — 4x fewer bytes
    # than f32 (the measured bottleneck of the r3 recorded runs) — with
    # normalization as the first op inside the compiled step (the
    # reference keeps it in-graph too, imagenet_preprocessing.py:
    # 397-430).  "float32": host-side normalization (r1-r3 wire).
    input_wire: str = "uint8"
    # --- host-side data service (dtf_tpu/data/service) ---
    # Imagenet TRAIN batches come from the sharded deterministic
    # multi-process service by default: batch n is a pure function of
    # (seed, process, n), so killed-at-K resume is bit-exact and decode
    # scales past the single-process GIL ceiling.  False = the legacy
    # threaded pipeline (fused native decode; NOT position-exact — a
    # mid-stream resume is refused loudly).
    input_service: bool = True
    # static shard count of the TFRecord file set.  Part of the stream's
    # identity: the merged batch order depends on it, so a resumed run
    # must keep the value the checkpoint was written with (validated
    # from host_state).  Size it >= input_workers; the default (16)
    # suits the production 1024-file layout — toy directories with
    # fewer files than shards fail loudly with the flag to lower.
    input_num_shards: int = 16
    # spawned shard-worker processes; -1 (default) = auto: one per
    # host core, capped by input_num_shards (inline when the host has
    # a single core); 0 = run every shard inline (no subprocess —
    # tests, benchmark baselines).  Worker count NEVER changes the
    # stream — workers only decide who computes a batch, not what the
    # batch is — so auto-sizing (and changing it across a resume) is
    # safe by construction.
    input_workers: int = -1
    # decode-once cache tier: directory for the per-shard mmap-backed
    # cache of decoded images ("" = off).  Epoch >= 2 and co-hosted
    # replicas skip JPEG decode entirely; cached and uncached runs are
    # bit-identical by construction.
    input_cache_dir: str = ""
    input_cache_limit_mb: int = 0       # per-shard cache byte bound; 0 = unbounded
    per_gpu_thread_count: int = 0       # no-op compat (common.py:143-166 is CUDA-only)  # dtflint: disable=flag-dead (declared no-op: CUDA-only knob, kept for reference CLI parity)
    tf_gpu_thread_mode: Optional[str] = None  # no-op compat  # dtflint: disable=flag-dead (declared no-op: CUDA-only knob, kept for reference CLI parity)
    batchnorm_spatial_persistent: bool = False  # no-op compat (cuDNN-only, common.py:368-377)  # dtflint: disable=flag-dead (declared no-op: cuDNN-only knob, kept for reference CLI parity)

    # --- image / data ---
    # --data_format (reference resnet_cifar_main.py:94-98): channels_first
    # means batches are fed NCHW; the train/eval steps transpose to NHWC
    # (compute is always NHWC — the MXU layout)
    data_format: str = "channels_last"
    use_synthetic_data: bool = False    # --use_synthetic_data (common.py:311-359)
    # Eval partial-batch handling.  False (default): eval pipelines pad
    # the final partial batch and mask the padding, so eval covers the
    # reference's exact full set (imagenet_preprocessing.py:259-323) with
    # static shapes.  True: drop it (every eval batch full — benchmark
    # purity).  Training always drops the remainder for static shapes
    # (imagenet_main.py:143-145 XLA parity).
    drop_remainder: bool = False
    image_bytes_as_serving_input: bool = False  # compat  # dtflint: disable=flag-dead (declared no-op: TF serving-signature knob with no orbax analog; kept for reference CLI parity)

    # --- keras-flags extras (common.py:248-309) ---
    enable_eager: bool = False          # no-op: JAX is eager outside jit by construction  # dtflint: disable=flag-dead (declared no-op by construction; kept for reference CLI parity)
    skip_eval: bool = False             # --skip_eval
    eval_only: bool = False             # evaluate (a restored checkpoint) and exit
    use_trivial_model: bool = False     # --use_trivial_model (imagenet_main.py:189-191)
    report_accuracy_metrics: bool = True  # --report_accuracy_metrics (common.py:277-278)
    use_tensor_lr: bool = False         # --use_tensor_lr → PiecewiseConstantDecayWithWarmup
    enable_tensorboard: bool = False    # --enable_tensorboard (common.py:187-190)
    train_steps: Optional[int] = None   # --train_steps cap (common.py)
    profile_steps: Optional[str] = None  # --profile_steps "start,stop" (common.py:289-296)
    # partial-batch handling (reference resnet_cifar_main.py:108-141):
    # True forces drop_remainder=False (eval covers the partial batch)
    enable_get_next_as_optional: bool = False
    log_steps: int = 100                # --log_steps for BenchmarkMetric cadence
    skip_checkpoint: bool = False       # rank-0 checkpoints off (horovod mains default on)
    resume: bool = False                # restore latest checkpoint from model_dir
    # preemption-granularity checkpointing: additionally save (sync,
    # sealed with an integrity manifest) every N global steps.  0 = the
    # reference's per-epoch-only cadence.  On preemptible pods the
    # epoch is far too coarse a recovery unit — a rank lost mid-epoch
    # re-trains the whole epoch
    checkpoint_steps: int = 0

    # --- benchmark (define_benchmark) ---
    benchmark_log_dir: str = ""         # --benchmark_log_dir
    benchmark_test_id: str = ""         # --benchmark_test_id

    # --- model / dataset selection ---
    model: str = ""                     # resnet50 | resnet56|resnet20|resnet32|resnet110 | trivial
    dataset: str = ""                   # cifar10 | imagenet
    num_classes: Optional[int] = None   # override (imagenet: 1001, cifar: 10)
    seq_len: Optional[int] = None       # override the LM dataset's sequence length

    # --- distribution / topology (TF_CONFIG successor) ---
    distribution_strategy: str = "mirrored"  # --distribution_strategy
    ps_mode: str = "sync"               # parameter_server flavor: sync SPMD
                                        # (north star) | async (C++ param
                                        # store, capability-exact, parallel/ps)
    ps_wire: str = "fp32"               # async-PS wire format: fp32 | bf16
                                        # (bf16 halves pull/push traffic;
                                        # store math stays fp32)
    # async-PS fault tolerance (r5): the PS rank restores from
    # <dir>/ps_store.snap at startup when present, snapshots
    # params+velocity+version there every ps_snapshot_secs (atomic
    # tmp+rename), and workers reconnect with backoff instead of dying
    # with the store.  None = the reference's behavior (in-memory only,
    # "Workers will need to restart training", ps_server/log1.log).
    ps_snapshot_dir: Optional[str] = None
    ps_snapshot_secs: float = 30.0
    ps_reconnect_secs: float = 300.0    # how long workers retry a dead
                                        # PS before giving up (only with
                                        # ps_snapshot_dir — reconnecting
                                        # to an unrestored store hangs)
    # how many store versions a restarted PS may trail what a worker
    # already saw before the worker refuses to continue (guard against
    # silently resuming a mid-schedule run on a store that lost its
    # state).  Size >= cluster pushes/sec x ps_snapshot_secs + margin.
    # Default single-sourced from parallel/ps.py DEFAULT_RESEED_TOLERANCE
    # (10,000); kept as a literal here because Config must import
    # without pulling the ps module — parity asserted by test_ps.
    ps_reseed_tolerance: int = 10_000
    num_devices: Optional[int] = None   # ≈ --num_gpus: local chips to use; None = all
    worker_hosts: Optional[str] = None  # --worker_hosts "h1:p,h2:p" (imagenet_main.py:108-110)
    task_index: int = -1                # --task_index
    coordinator_address: Optional[str] = None  # jax.distributed coordinator
    process_id: Optional[int] = None
    process_count: Optional[int] = None
    # mesh axis sizes; data axis is inferred from the rest (SURVEY §5.7:
    # keep model/seq axes open even though the reference is DP-only)
    model_parallelism: int = 1          # size of the 'model' mesh axis
    seq_parallelism: int = 1            # size of the 'seq' mesh axis (ring attention)
    # column-parallel lm_head over 'model' (Megatron vocab-parallel
    # softmax): local logits + collective CE; transformer family only
    shard_lm_head: bool = False
    sync_bn: bool = False               # cross-replica BN (reference default: per-replica)

    # --- mixture-of-experts (moe_transformer family) ---
    # None = the model preset's own default (e.g. moe_transformer_small
    # ships 4 experts); set a value to override it
    num_experts: Optional[int] = None   # total experts; sharded over 'data' (EP)
    moe_capacity_factor: Optional[float] = None  # per-expert capacity multiplier
    moe_aux_weight: Optional[float] = None  # load-balance aux-loss weight
    moe_top_k: Optional[int] = None     # router choices: 1=Switch, 2=GShard
    # --- pipeline parallelism (pipeline_transformer family) ---
    num_microbatches: Optional[int] = None  # GPipe microbatches per step
    # 2 = two virtual stages per device (Megatron interleaving): halves
    # the fill/drain bubble at equal num_microbatches for the cost of
    # 2x ppermute hops (models/pipeline_lm.py docstring)
    pipeline_interleave: int = 1

    # --- optimizer ---
    optimizer: str = "sgd"              # sgd (reference, common.py:169-172)
                                        # | adamw (transformer LM recipe)
    # gradient accumulation: each step runs this many sequential
    # microbatch fwd/bwd passes per replica before one update — trains
    # reference-scale global batches on fewer chips
    grad_accum_steps: int = 1
    # rematerialization (jax.checkpoint) around each transformer block:
    # trade recompute FLOPs for HBM — the long-context memory lever
    remat: bool = False
    # selective remat (implies --remat): "dots" saves matmul/attention
    # outputs and recomputes only elementwise ops in the backward — a
    # cheaper memory lever than full remat (no MXU recompute), for
    # contexts where activations don't fit without remat
    # (models/transformer.py remat_policy has the measured frontier)
    remat_policy: Optional[str] = None
    # clip gradients to this global L2 norm (computed across every
    # shard of every parameter); None = no clipping
    clip_grad_norm: Optional[float] = None
    # ZeRO-1 / weight-update sharding (Xu et al. 2020, "Automatic
    # Cross-Replica Sharding of Weight Update in Data-Parallel
    # Training"): reduce-scatter gradients, update a 1/N parameter
    # slice per data shard with 1/N optimizer state, all-gather the
    # updated params — optimizer memory and update FLOPs drop by the
    # data-parallel degree at equal communication volume.  Kept as the
    # stage-1 shorthand; --zero_stage is the full lever
    optimizer_sharding: bool = False
    # ZeRO stage on the data axis (train/loop.py, train/zero.py):
    #   0 = replicated everything (plain DP)
    #   1 = sharded optimizer state (≡ --optimizer_sharding)
    #   2 = + sharded gradients: each microbatch's grads reduce-scatter
    #       into 1/N slices as the backward produces them (per-leaf, so
    #       XLA's latency-hiding scheduler overlaps the collectives
    #       with compute); the grad-accumulation buffer shrinks by the
    #       data-parallel degree
    #   3 = + sharded parameters: params live as 1/N flat slices and
    #       are all-gathered per leaf at the top of each step — a model
    #       whose replicated state does not fit one device trains
    # Every stage is mathematically identical to plain DP (test-pinned
    # within the documented float tolerance); checkpoints are written
    # in the canonical stage-0 layout, so any stage restores into any
    # other and into serving via the bridge
    zero_stage: int = 0
    # ZeRO-2/3 grad reduce-scatter WIRE format: fp32 (default) | bf16.
    # bf16 halves the per-microbatch scatter volume — the collective
    # then also sums in bf16 (the --ps_wire bf16 trade, applied to the
    # FSDP path); the slices and the cross-microbatch accumulation
    # stay f32 (train/zero.py scatter_leaf).  Documented loss
    # tolerance vs the f32 wire is pinned by tests/test_zero_stages.py
    zero_wire: str = "fp32"
    # measure the ZeRO collective cost (stages >= 2): time standalone
    # reduce-scatter/all-gather probes plus a comm-stubbed twin of the
    # compiled step, and export train_zero_*_wall_s +
    # train_exposed_comm_frac gauges through the MFU ledger.  Costs one
    # extra step compile — a bench/smoke lever, not a production
    # default
    zero_probe: bool = False

    # --- serving (cli/serve_main.py over dtf_tpu/serve) ---
    serve_max_batch: int = 8            # decode slots = max concurrent sequences
    serve_max_delay_ms: float = 5.0     # batch-fill window after first arrival
    serve_queue_size: int = 64          # bounded admission queue (backpressure)
    serve_max_seq_len: Optional[int] = None  # cache capacity; None = model max
    serve_max_new_tokens: int = 32      # per-request generation budget (demo)
    serve_temperature: float = 0.0      # 0 = greedy
    serve_requests: int = 16            # synthetic-traffic demo request count
    serve_prompt_len: int = 8           # synthetic prompt length (max; varied)
    # paged KV cache (serve/engine.py, ops/paged_attention.py): tokens
    # per KV page; 0 = the legacy contiguous per-slot cache.  With
    # paging, HBM admission is bounded by tokens in flight, not
    # num_slots x max_seq_len
    kv_page_size: int = 16
    # total pool pages INCLUDING the scratch page; 0 = the full
    # contiguous-equivalent reservation (1 + slots x pages-per-slot).
    # Size it down (e.g. 50%) when mean request length << max_seq_len
    kv_pool_pages: int = 0
    # chunked-prefill unit in tokens (multiple of kv_page_size): long
    # prompts prefill one chunk per engine iteration, interleaved with
    # decode steps for running slots; 0 = whole-prompt single chunk;
    # None (default) = 4 pages, valid at ANY page size
    serve_prefill_chunk: Optional[int] = None
    # serving tensor parallelism: shard decode params (Megatron
    # column/row layout) and every layer's KV page pool (head dim)
    # over a 'model' mesh axis of this many chips — the bridge
    # restores train/export/ZeRO checkpoints DIRECTLY into the sharded
    # layout, so a model that trains sharded never has to fit on one
    # chip to serve.  Needs the paged cache (kv_page_size > 0)
    serve_tp: int = 1
    # prefix sharing (paged cache): refcounted pages + a token-id-hash
    # registry of full prompt-prefix pages — N requests sharing a
    # system prompt cost ONE physical copy; copy-on-write protects the
    # one shared-page write (serve/engine.py module docs)
    serve_prefix_sharing: bool = True

    # --- serving replica tier (serve/router.py over cli/replica_main) ---
    # replica serve processes behind the router (cli/router_main.py);
    # each is a full ServeEngine (optionally TP-sharded via --serve_tp)
    router_replicas: int = 2
    # default per-request deadline: the router resolves every accepted
    # request — tokens, Backpressure, or DeadlineExceeded — within it
    router_deadline_s: float = 120.0
    # router-level admission bound: outstanding (queued + in-flight)
    # requests beyond this shed loudly with Backpressure(retry_after)
    router_admission: int = 128
    # health-probe cadence (reads each replica's heartbeat_rank{K}.json)
    router_probe_s: float = 0.5
    # heartbeat silence past this = the replica is declared lost (its
    # in-flight re-dispatches; must be comfortably > --heartbeat_secs)
    router_health_timeout_s: float = 15.0
    # per-replica in-flight dispatch cap; 0 = auto (serve_queue_size)
    router_replica_inflight: int = 0
    # replica respawn budget: at most this many respawns per sliding
    # window, exponential backoff between them, then loud give-up —
    # the launcher supervisor's crash discipline, per replica
    router_max_respawns: int = 8
    router_respawn_window_s: float = 300.0
    router_respawn_backoff_s: float = 0.5
    # hedge: re-dispatch a request to a second replica when its first
    # makes no progress for this long (greedy decode makes the copies
    # token-identical; first done wins).  0 = off
    router_hedge_s: float = 0.0
    # placement policy: prefix-affine (route by chained prompt-page
    # digest to the replica whose PrefixRegistry is warm, least-loaded
    # fallback) | least_loaded | random (the bench A/B arm)
    router_placement: str = "affinity"
    # disaggregation: replicas 0..N-1 form a prefill-specialized pool,
    # the rest a decode pool — cold prompts prefill in the first,
    # their KV-page chains migrate over the wire (serve/migrate.py)
    # and re-home to the second, so warm shared-prefix traffic decodes
    # prefill-free.  Needs router_placement=affinity.  0 = colocated
    # (the default: every replica does both, no migration)
    router_prefill_replicas: int = 0
    # rendezvous directory for announce + heartbeat files (router +
    # cli/replica_main); "" = router_main picks a temp dir.  Put it on
    # SHARED storage and the tier goes cross-host: replicas announce
    # host:port (--serve_host) and register/heal identically to local
    # ones — the wire is plain TCP
    rendezvous_dir: str = ""
    # replica identity for cli/replica_main; -1 = from DTF_PROCESS_ID
    replica_id: int = -1
    # address a replica binds AND announces (replica_rank{K}.json
    # "host" field): 127.0.0.1 = single-host loopback (default); a
    # routable address makes the replica reachable from a router on
    # another host
    serve_host: str = "127.0.0.1"
    # --- router high availability (serve/journal.py + serve/ha.py) ---
    # journal every request's lifecycle to router_journal.jsonl in the
    # rendezvous dir and take the shared-storage leader lease: a
    # successor router (restart or warm standby) replays the journal
    # and re-adopts in-flight requests exactly-once.  Off by default —
    # a single-router tier pays zero overhead.
    router_ha: bool = False
    # run router_main as the WARM STANDBY: wait for the leader's lease
    # to expire, then take over under the next fencing epoch (implies
    # router_ha; never spawns replicas — the leader owns them)
    router_standby: bool = False
    # leader-lease time-to-live: the standby takes over after the
    # leader misses ~1 TTL of renewals (renewal cadence is TTL/3)
    router_lease_ttl_s: float = 2.0
    # bounded journal fsync cadence: a HOST crash loses at most this
    # much journal tail (a process crash loses nothing — every append
    # is flushed)
    router_journal_fsync_s: float = 0.05

    # --- zero-downtime rollout (serve/rollout.py over the router) ---
    # rollout the tier onto this checkpoint (a model_dir or
    # export_dir path) mid-traffic: drain one replica at a time,
    # canary-gate the first against the old model token-by-token,
    # auto-rollback on breach.  "" = no rollout
    rollout_checkpoint: str = ""
    # completed old-vs-new comparisons the canary gate requires
    rollout_canary_requests: int = 4
    # slice of live greedy traffic mirrored to the canary (0, 1]
    rollout_mirror_fraction: float = 1.0
    # gate threshold on diverged/compared; 0.0 = token-exact (any
    # single divergence rolls back — the bench_gate discipline:
    # identical models compare EQUAL, so a mismatch is signal)
    rollout_max_divergence: float = 0.0
    # how long a restarted replica gets to warm + re-register before
    # the rollout declares the new checkpoint unserveable + rolls back
    rollout_warm_timeout_s: float = 600.0
    # persisted rollout state file; "" = <rendezvous>/rollout_state
    # .json — a router restarted mid-rollout resumes or rolls back
    # deterministically from it
    rollout_state: str = ""

    # --- parallelism planner (dtf_tpu/plan) ---
    # "" = off (hand-set flags rule, the pre-planner behavior);
    # "auto" = search the feasible plan lattice on --plan_mesh and
    # compile the fastest predicted plan into the parallelism flags;
    # <path> = a plan JSON (plan_main --out artifact, a {"plan": ...}
    # wrapper, or a bare plan object).  A plan-selected run is
    # bit-identical to the same flags set by hand (tests/test_plan.py);
    # plan-owned flags (--model_parallelism & co.) must stay at their
    # defaults when --plan is given — conflicts are loud errors.
    plan: str = ""
    # mesh descriptor the planner costs against: "" = the live runtime
    # topology, a preset (cpu | v4-8 | 4x4), or an explicit
    # "hosts=4,devices=4,hbm=32g,flops=140t,intra=100g,inter=25g"
    plan_mesh: str = ""
    # ranked-lattice memoization sidecar (plan/cache.py): a JSON file
    # keyed by (workload, mesh descriptor, batch) — repeated
    # `--plan auto` resolves (launcher restarts!) and plan_main
    # rankings skip the search on a hit.  "" = off
    plan_cache: str = ""
    # cross-run checkpoint GC by verified-set (train/checkpoint.py
    # Checkpointer.gc): after training, delete all but the newest N
    # sha256-VERIFIED steps (steps newer than the newest verified one —
    # e.g. an in-flight unsealed save — are never touched; with no
    # verified step at all nothing is deleted).  0 = off (orbax's
    # in-run max_to_keep still applies)
    checkpoint_keep: int = 0

    # --- observability (dtf_tpu/obs) ---
    # structured JSONL tracing: each process writes
    # <trace_dir>/trace_rank{N}.jsonl (step/compile/checkpoint/ps/serve
    # spans + anomaly events); summarize with
    # `python -m dtf_tpu.cli.trace_main <trace_dir>`.  "" = off (the
    # DTF_TRACE_DIR env var — forwarded by the launcher — also enables)
    trace_dir: str = ""
    # abort loudly (structured anomaly + TrainingAnomaly) on the first
    # non-finite loss that reaches the host; checked at --log_steps
    # cadence on the value the loop already syncs — no extra device
    # round-trip
    nan_guard: bool = True
    # flag a log window taking > factor x the rolling median of recent
    # windows (input-pipeline stall / straggler signature); reports,
    # never aborts.  0 disables.
    step_time_guard_factor: float = 3.0
    # heartbeat file rewrite interval (launcher supervision); the file
    # is only written when the launcher exports DTF_HEARTBEAT_DIR
    heartbeat_secs: float = 5.0
    # live scrape endpoint: the owning registry as Prometheus text
    # over stdlib http.server on this port (GET /metrics) plus a
    # GET /healthz JSON probe (200/503).  Train: rank 0, the default
    # registry.  router_main: the router registry on this port and
    # replica K's engine registry on port+1+K (one flag makes the
    # whole tier scrapable).  replica_main standalone: the engine
    # registry.  0 = off (the default)
    metrics_port: int = 0
    # poll the GCE/TPU metadata preemption endpoint every N seconds in
    # a daemon thread; a pending preemption feeds the SIGTERM latch
    # (train/preemption.py), so the emergency-checkpoint path runs even
    # when the scheduler signals via metadata before the SIGTERM lands.
    # 0 = off (the default — most schedulers do deliver SIGTERM).
    # DTF_METADATA_URL overrides the endpoint (tests, other clouds)
    preemption_poll_s: float = 0.0

    # --- chaos (dtf_tpu/chaos: deterministic fault injection) ---
    # comma-separated fault specs, e.g. "crash@step:120",
    # "sigterm@rank1:step:80", "ps_drop@version:50",
    # "heartbeat_stall@step:60", "ckpt_truncate@latest"; serving
    # replica tier: "replica_kill@req:6" (router SIGKILLs the Nth
    # dispatch's replica), "net_partition@replica1:12" (drop replica
    # 1's health probes for 12 prober ticks), "slow_replica@replica1:4"
    # (4x decode steps in replica 1).  "" = off (the DTF_FAULT env var
    # also arms it).  Provably zero-cost when unset: every probe is a
    # module-level None check (tests/test_chaos.py)
    fault: str = ""

    # --- misc ---
    seed: int = 0
    verbose: int = 2                    # keras fit verbose parity (rank-gated)

    def __post_init__(self):
        if self.data_format not in ("channels_last", "channels_first"):
            raise ValueError(
                f"unknown data_format {self.data_format!r}; choose "
                f"channels_last or channels_first")
        if self.enable_get_next_as_optional and self.drop_remainder:
            # reference semantics: get_next_as_optional exists to handle
            # the partial final batch — forcing drop would contradict it
            self.drop_remainder = False
        if self.distribution_strategy not in STRATEGIES:
            raise ValueError(
                f"unknown distribution_strategy {self.distribution_strategy!r}; "
                f"choose from {STRATEGIES}")
        if self.dtype not in DTYPES:
            raise ValueError(f"unknown dtype {self.dtype!r}; choose from {DTYPES}")
        if self.pipeline_interleave not in (1, 2):
            raise ValueError(
                f"pipeline_interleave must be 1 or 2, got "
                f"{self.pipeline_interleave}")
        if self.input_wire not in ("uint8", "float32"):
            raise ValueError(
                f"unknown input_wire {self.input_wire!r}; choose uint8 "
                f"or float32")
        if self.ps_wire not in ("fp32", "bf16"):
            raise ValueError(
                f"unknown ps_wire {self.ps_wire!r}; choose fp32 or bf16")
        if self.ps_mode not in ("sync", "async"):
            raise ValueError(
                f"unknown ps_mode {self.ps_mode!r}; choose sync or async")
        if self.optimizer not in ("sgd", "momentum", "adamw"):
            raise ValueError(
                f"unknown optimizer {self.optimizer!r}; choose sgd or adamw")
        if self.loss_scale is not None:
            if str(self.loss_scale).lower() != "dynamic":
                try:
                    val = float(self.loss_scale)
                except (TypeError, ValueError):
                    raise ValueError(
                        f"loss_scale must be a number or 'dynamic', got "
                        f"{self.loss_scale!r}") from None
                import math
                if not math.isfinite(val) or val <= 0:
                    raise ValueError(
                        f"loss_scale must be a positive finite number, "
                        f"got {val}")
        if self.zero_stage not in (0, 1, 2, 3):
            raise ValueError(
                f"zero_stage must be 0, 1, 2 or 3, got {self.zero_stage}")
        if self.optimizer_sharding and self.zero_stage >= 2:
            raise ValueError(
                "--optimizer_sharding is the ZeRO stage-1 shorthand and "
                "contradicts --zero_stage >= 2 — pass only --zero_stage")
        if self.zero_probe and self.zero_stage < 2:
            raise ValueError(
                "--zero_probe measures the stage-2/3 collectives; it "
                "needs --zero_stage 2 or 3")
        if self.zero_wire not in ("fp32", "bf16"):
            raise ValueError(
                f"unknown zero_wire {self.zero_wire!r}; choose fp32 "
                f"or bf16")
        if self.zero_wire == "bf16" and self.zero_stage_effective < 2:
            raise ValueError(
                "--zero_wire bf16 rides the stage-2/3 grad "
                "reduce-scatter; it needs --zero_stage 2 or 3")
        if self.clip_grad_norm is not None:
            import math
            if (not math.isfinite(self.clip_grad_norm)
                    or self.clip_grad_norm <= 0):
                raise ValueError(
                    f"clip_grad_norm must be a positive finite number, "
                    f"got {self.clip_grad_norm}")
        if self.eval_only and self.skip_eval:
            raise ValueError("--eval_only contradicts --skip_eval")
        if self.stop_threshold is not None and not self.report_accuracy_metrics:
            raise ValueError(
                "--stop_threshold needs eval top-1, which "
                "--report_accuracy_metrics false disables — early "
                "stopping would silently never fire")
        if self.moe_top_k is not None and self.moe_top_k < 1:
            raise ValueError(f"moe_top_k must be >= 1, got {self.moe_top_k}")
        if self.serve_max_batch < 1 or self.serve_queue_size < 1:
            raise ValueError(
                "serve_max_batch and serve_queue_size must be >= 1")
        if self.kv_page_size < 0 or self.kv_pool_pages < 0 or (
                self.serve_prefill_chunk is not None
                and self.serve_prefill_chunk < 0):
            raise ValueError(
                "kv_page_size, kv_pool_pages and serve_prefill_chunk "
                "must be >= 0 (0 disables each)")
        if (self.kv_page_size and self.serve_prefill_chunk
                and self.serve_prefill_chunk % self.kv_page_size):
            raise ValueError(
                f"serve_prefill_chunk ({self.serve_prefill_chunk}) must "
                f"be a multiple of kv_page_size ({self.kv_page_size})")
        if not self.kv_page_size and (
                self.kv_pool_pages or self.serve_prefill_chunk is not None):
            raise ValueError(
                "kv_pool_pages / serve_prefill_chunk need the paged "
                "cache (kv_page_size > 0)")
        if self.serve_tp < 1:
            raise ValueError(f"serve_tp must be >= 1, got {self.serve_tp}")
        if self.serve_tp > 1 and not self.kv_page_size:
            raise ValueError(
                "serve_tp > 1 (tensor-parallel serving) needs the paged "
                "KV cache (kv_page_size > 0) — the page pool is the "
                "layout that shards")
        if self.router_replicas < 1:
            raise ValueError(
                f"router_replicas must be >= 1, got {self.router_replicas}")
        if self.router_deadline_s <= 0 or self.router_admission < 1:
            raise ValueError(
                "router_deadline_s must be > 0 and router_admission >= 1")
        if self.router_probe_s <= 0 or (
                self.router_probe_s >= self.router_health_timeout_s):
            raise ValueError(
                f"router_probe_s ({self.router_probe_s}) must be > 0 and "
                f"< router_health_timeout_s "
                f"({self.router_health_timeout_s}) — a health verdict "
                f"needs multiple probe ticks")
        if self.router_health_timeout_s <= 0:
            raise ValueError(
                f"router_health_timeout_s must be > 0, got "
                f"{self.router_health_timeout_s}")
        # NOTE: health_timeout vs heartbeat_secs is cross-checked in
        # cli/router_main.py, not here — a training-only run raising
        # --heartbeat_secs must not be rejected over router defaults
        # it never uses
        # literal copy of serve/router.py PLACEMENTS: Config must import
        # without pulling the serve stack (flax models); parity is
        # pinned by tests/test_router.py
        if self.router_placement not in ("affinity", "least_loaded",
                                         "random"):
            raise ValueError(
                f"unknown router_placement {self.router_placement!r}; "
                f"choose from ('affinity', 'least_loaded', 'random')")
        if self.router_prefill_replicas < 0 or (
                self.router_prefill_replicas >= self.router_replicas
                and self.router_prefill_replicas > 0):
            raise ValueError(
                f"router_prefill_replicas "
                f"({self.router_prefill_replicas}) must leave at least "
                f"one decode replica (router_replicas="
                f"{self.router_replicas})")
        if (self.router_prefill_replicas
                and self.router_placement != "affinity"):
            raise ValueError(
                "router_prefill_replicas needs router_placement="
                "affinity — chain re-homing rides the prefix-owner map")
        if (self.router_replica_inflight < 0 or self.router_max_respawns
                < 0 or self.router_respawn_backoff_s < 0
                or self.router_hedge_s < 0):
            raise ValueError(
                "router_replica_inflight/router_max_respawns/"
                "router_respawn_backoff_s/router_hedge_s must be >= 0")
        if not self.serve_host:
            raise ValueError(
                "serve_host must be a bindable address (127.0.0.1 for "
                "single-host, a routable address for cross-host)")
        if self.router_lease_ttl_s <= 0:
            raise ValueError(
                f"router_lease_ttl_s must be > 0, got "
                f"{self.router_lease_ttl_s}")
        if self.router_journal_fsync_s < 0:
            raise ValueError(
                f"router_journal_fsync_s must be >= 0, got "
                f"{self.router_journal_fsync_s}")
        if self.router_standby and not self.rendezvous_dir:
            raise ValueError(
                "router_standby needs an explicit --rendezvous_dir — "
                "the standby finds the leader's lease, journal and "
                "replicas there (a temp dir of its own would watch "
                "an empty tier)")
        if self.rollout_canary_requests < 1:
            raise ValueError(
                f"rollout_canary_requests must be >= 1, got "
                f"{self.rollout_canary_requests}")
        if not 0.0 < self.rollout_mirror_fraction <= 1.0:
            raise ValueError(
                f"rollout_mirror_fraction must be in (0, 1], got "
                f"{self.rollout_mirror_fraction}")
        if not 0.0 <= self.rollout_max_divergence <= 1.0:
            raise ValueError(
                f"rollout_max_divergence must be in [0, 1], got "
                f"{self.rollout_max_divergence}")
        if self.rollout_warm_timeout_s <= 0:
            raise ValueError(
                f"rollout_warm_timeout_s must be > 0, got "
                f"{self.rollout_warm_timeout_s}")
        if self.rollout_checkpoint and self.serve_temperature > 0:
            raise ValueError(
                "rollout_checkpoint needs greedy demo traffic "
                "(--serve_temperature 0): the canary gate compares "
                "mirrored GREEDY requests token-by-token — sampled "
                "traffic is never mirrored, so the gate would starve "
                "and every rollout would time out into a rollback")
        if self.rollout_checkpoint and self.router_replicas < 2:
            raise ValueError(
                "rollout_checkpoint needs >= 2 router_replicas — the "
                "shadow-only canary must not be the tier's only "
                "replica")
        if self.step_time_guard_factor and self.step_time_guard_factor <= 1.0:
            raise ValueError(
                f"step_time_guard_factor must be > 1.0 (or 0 to disable), "
                f"got {self.step_time_guard_factor}")
        if self.heartbeat_secs <= 0:
            raise ValueError(
                f"heartbeat_secs must be positive, got {self.heartbeat_secs}")
        if not 0 <= self.metrics_port <= 65535:
            raise ValueError(
                f"metrics_port must be in [0, 65535] (0 = off), got "
                f"{self.metrics_port}")
        if self.preemption_poll_s < 0:
            raise ValueError(
                f"preemption_poll_s must be >= 0 (0 = off), got "
                f"{self.preemption_poll_s}")
        if self.input_num_shards < 1:
            raise ValueError(
                f"input_num_shards must be >= 1, got "
                f"{self.input_num_shards}")
        if self.input_workers < -1:
            raise ValueError(
                f"input_workers must be >= -1 (-1 = auto, 0 = inline), "
                f"got {self.input_workers}")
        if self.input_cache_limit_mb < 0:
            raise ValueError(
                f"input_cache_limit_mb must be >= 0 (0 = unbounded), "
                f"got {self.input_cache_limit_mb}")
        if self.input_cache_limit_mb and not self.input_cache_dir:
            raise ValueError(
                "input_cache_limit_mb needs --input_cache_dir (the "
                "decode-once cache is off without a directory)")
        if self.checkpoint_steps < 0:
            raise ValueError(
                f"checkpoint_steps must be >= 0 (0 = per-epoch only), "
                f"got {self.checkpoint_steps}")
        if self.checkpoint_keep < 0:
            raise ValueError(
                f"checkpoint_keep must be >= 0 (0 = no cross-run GC), "
                f"got {self.checkpoint_keep}")
        if self.plan and self.plan != "auto" and not os.path.exists(self.plan):
            # fail at flag-parse time, not after dataset/model setup
            raise ValueError(
                f"--plan {self.plan!r}: no such plan file (pass 'auto' "
                f"to search, or a plan_main --out JSON artifact)")
        if self.plan_mesh:
            # typo'd presets/descriptors fail at flag-parse time, not
            # mid-resolution (mesh_spec never touches jax for a
            # non-empty spec, so this stays import-light)
            from dtf_tpu.plan.mesh_spec import mesh_spec
            mesh_spec(self.plan_mesh)
        if self.fault:
            # fail at flag-parse time, not at the step the typo'd fault
            # silently never fires
            from dtf_tpu import chaos
            chaos.parse_spec(self.fault)
        if self.eval_only and not self.resume:
            raise ValueError(
                "--eval_only evaluates a restored checkpoint; pass "
                "--resume (and --model_dir) or there is nothing to "
                "evaluate but random init")

    @property
    def zero_stage_effective(self) -> int:
        """The ZeRO stage a run executes: --zero_stage when set,
        else 1 under the --optimizer_sharding shorthand, else 0."""
        return self.zero_stage or (1 if self.optimizer_sharding else 0)

    # -- dtype helpers -------------------------------------------------
    @property
    def compute_dtype(self):
        import jax.numpy as jnp
        if self.dtype in ("bf16", "bfloat16"):
            return jnp.bfloat16
        if self.dtype in ("fp16", "float16"):
            return jnp.float16
        return jnp.float32

    @property
    def loss_scale_value(self):
        """Parity with flags_core.get_loss_scale: fp16 defaults to a
        static 128; ``--loss_scale dynamic`` returns the string
        "dynamic" (TF2 LossScaleOptimizer semantics, handled by the
        train loop)."""
        if self.loss_scale is not None:
            if str(self.loss_scale).lower() == "dynamic":
                return "dynamic"
            return float(self.loss_scale)
        return 128.0 if self.dtype in ("fp16", "float16") else 1.0

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _coerce(field: dataclasses.Field, raw: str) -> Any:
    t = field.type
    if raw.lower() in ("none", "null"):
        return None
    if t in ("bool", bool):
        return raw.lower() in ("true", "1", "yes", "t")
    if "int" in str(t):
        return int(raw)
    if "float" in str(t):
        return float(raw)
    return raw


def define_flags() -> dict:
    """Returns {flag_name: default} — the full registry, for docs/tests."""
    return {f.name: f.default for f in dataclasses.fields(Config)}


def parse_flags(argv=None, defaults: Optional[dict] = None) -> Config:
    """absl-style parsing: accepts ``--flag value``, ``--flag=value``,
    ``-flag value`` and bare boolean flags (``--skip_eval``).

    ``defaults`` plays the role of ``flags_core.set_defaults`` — the
    per-dataset defaults each main sets (reference cifar_main.py:226-230).
    """
    names = {f.name: f for f in dataclasses.fields(Config)}
    kw = dict(defaults or {})
    argv = list(argv or [])
    i = 0
    while i < len(argv):
        tok = argv[i]
        if not tok.startswith("-"):
            raise ValueError(f"unexpected argument {tok!r}")
        name = tok.lstrip("-")
        val = None
        if "=" in name:
            name, val = name.split("=", 1)
        if name not in names:
            raise ValueError(f"unknown flag --{name}")
        fld = names[name]
        if val is None:
            nxt = argv[i + 1] if i + 1 < len(argv) else None
            if fld.type in ("bool", bool) and (
                    nxt is None or nxt.startswith("-") or
                    nxt.lower() not in ("true", "false", "1", "0", "yes", "no", "t", "f")):
                val, step = "true", 1
            else:
                if nxt is None:
                    raise ValueError(f"flag --{name} needs a value")
                val, step = nxt, 2
        else:
            step = 1
        kw[name] = _coerce(fld, val)
        i += step
    cfg = Config(**kw)
    return apply_env_topology(cfg)


def topology_from_env() -> dict:
    """Read per-process identity from the environment.

    Two sources, in priority order:
      1. DTF_COORDINATOR / DTF_PROCESS_ID / DTF_PROCESS_COUNT — native.
      2. TF_CONFIG JSON — drop-in parity with the reference's cluster
         contract (ps_server/resnet_imagenet_main_dist_ps_0.py:40-50):
         {"cluster": {"worker": [host:port, ...]}, "task": {"type","index"}}.
         The first worker doubles as the coordination-service host.
    """
    out: dict = {}
    if os.environ.get("DTF_COORDINATOR"):
        out["coordinator_address"] = os.environ["DTF_COORDINATOR"]
    if os.environ.get("DTF_PROCESS_ID"):
        out["process_id"] = int(os.environ["DTF_PROCESS_ID"])
    if os.environ.get("DTF_PROCESS_COUNT"):
        out["process_count"] = int(os.environ["DTF_PROCESS_COUNT"])
    if out:
        return out

    tf_config = os.environ.get("TF_CONFIG")
    if tf_config:
        try:
            spec = json.loads(tf_config)
        except json.JSONDecodeError:
            return out
        cluster = spec.get("cluster", {})
        task = spec.get("task", {})
        workers = list(cluster.get("worker", []))
        ps = list(cluster.get("ps", []))
        # Flatten: ps ranks first then workers, matching the reference's
        # rank numbering where ps_0 is rank 0 (SURVEY §3.4).
        all_procs = ps + workers
        if all_procs:
            out["coordinator_address"] = all_procs[0]
            out["process_count"] = len(all_procs)
            ttype, tidx = task.get("type"), int(task.get("index", 0))
            out["process_id"] = tidx if ttype == "ps" else len(ps) + tidx
    return out


def apply_env_topology(cfg: Config) -> Config:
    """Fill unset topology fields from the environment; explicit flags win."""
    env = topology_from_env()
    kw = {}
    for k, v in env.items():
        if getattr(cfg, k) is None:
            kw[k] = v
    # --worker_hosts/--task_index parity (imagenet_main.py:108-110)
    if cfg.worker_hosts and cfg.coordinator_address is None and "coordinator_address" not in kw:
        hosts = [h.strip() for h in cfg.worker_hosts.split(",") if h.strip()]
        kw["coordinator_address"] = hosts[0]
        kw["process_count"] = len(hosts)
        if cfg.task_index >= 0:
            kw["process_id"] = cfg.task_index
    return cfg.replace(**kw) if kw else cfg
