from dtf_tpu.config.flags import (  # noqa: F401
    Config,
    define_flags,
    parse_flags,
    topology_from_env,
)
