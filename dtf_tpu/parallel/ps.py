"""Asynchronous parameter-server training — the opt-in capability-exact
mode (`--distribution_strategy parameter_server --ps_mode async`).

The reference's PS path (SURVEY §3.4): rank 0 hosts variables in the TF
grpc C++ runtime and serves push/pull forever; 15 workers each run an
independent `model.fit` with `steps_per_epoch = train_steps // 15`,
pulling params and pushing gradients per step with **no inter-worker
synchronization** (per-worker epoch times diverge 652→1008 s, SURVEY
§6).  XLA SPMD is synchronous by construction, so this mode keeps the
async semantics *outside* the compiled step: a native C++ parameter
store (`native/ps_store.cpp`) holds the flat parameter vector plus
Keras-SGD momentum slots, and each worker process runs its own jitted
forward/backward on its own chips, exchanging flat f32 buffers with the
store over TCP.  The synchronous SPMD reinterpretation
(`--ps_mode sync`, the default) remains the supported
performance path (BASELINE.json north star).

Rank mapping matches the reference deployment: process_id 0 is the PS
(ps_server/resnet_imagenet_main_dist_ps_0.py is the PS rank), 1..N are
workers 0..N-1.
"""

from __future__ import annotations

import ctypes
import itertools
import logging
import os
import socket
import struct
import threading
import time
from typing import Optional, Tuple

import numpy as np

from dtf_tpu import chaos
from dtf_tpu import native as native_lib
from dtf_tpu.obs import trace
from dtf_tpu.obs.registry import default_registry

log = logging.getLogger("dtf_tpu")

(OP_INIT, OP_PULL, OP_PUSH, OP_INFO, OP_DONE, OP_SHUTDOWN,
 OP_PULL16, OP_PUSH16) = 1, 2, 3, 4, 5, 6, 7, 8


def _f32_to_bf16_bytes(a: np.ndarray) -> bytes:
    """Round-to-nearest-even f32 -> bf16, as raw u16 little-endian.
    NaNs are preserved explicitly (truncate + force the quiet bit) —
    the RNE add can carry a low-mantissa NaN payload into Inf or even
    wrap to zero, silently masking a diverged gradient.

    Dispatches to the native one-pass conversion when built (VERDICT
    r3 #6: the numpy form's full-array temporaries under the GIL cost
    more than the loopback wire saved); the numpy fallback is
    bit-identical (tests/test_ps.py pins it)."""
    a = np.ascontiguousarray(a, np.float32)
    lib = native_lib.load()
    if lib is not None and hasattr(lib, "dtf_f32_to_bf16"):
        out = np.empty(a.shape, np.uint16)
        lib.dtf_f32_to_bf16(
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            a.size)
        return out.tobytes()
    u = a.view(np.uint32)
    r = ((u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1)))
         >> np.uint32(16)).astype(np.uint16)
    is_nan = ((u & np.uint32(0x7F800000)) == np.uint32(0x7F800000)) \
        & ((u & np.uint32(0x007FFFFF)) != 0)
    nan_out = ((u >> np.uint32(16)).astype(np.uint16)
               | np.uint16(0x0040))
    return np.where(is_nan, nan_out, r).astype(np.uint16).tobytes()


def _bf16_bytes_to_f32(b: bytes) -> np.ndarray:
    lib = native_lib.load()
    if lib is not None and hasattr(lib, "dtf_bf16_to_f32"):
        src = np.frombuffer(b, np.uint16)
        out = np.empty(src.shape, np.float32)
        lib.dtf_bf16_to_f32(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            src.size)
        return out
    u = np.frombuffer(b, np.uint16).astype(np.uint32) << np.uint32(16)
    return u.view(np.float32)

# Matches the C++ store's kMaxParams: a client-supplied count above this
# is a corrupt/hostile request, not a real model (4B f32 = 16 GiB).
MAX_PARAMS = 1 << 32


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

def _bind_native(lib: ctypes.CDLL) -> None:
    if getattr(lib, "_ps_bound", False):
        return
    lib.dtf_ps_start.argtypes = [ctypes.c_int, ctypes.c_float]
    lib.dtf_ps_start.restype = ctypes.c_void_p
    lib.dtf_ps_port.argtypes = [ctypes.c_void_p]
    lib.dtf_ps_port.restype = ctypes.c_int
    lib.dtf_ps_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dtf_ps_stop.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dtf_ps_snapshot"):  # stale .so tolerated (degrades)
        lib.dtf_ps_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.dtf_ps_snapshot.restype = ctypes.c_int
        lib.dtf_ps_restore.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.dtf_ps_restore.restype = ctypes.c_int
    if hasattr(lib, "dtf_ps_start_paused"):
        lib.dtf_ps_start_paused.argtypes = [ctypes.c_int, ctypes.c_float]
        lib.dtf_ps_start_paused.restype = ctypes.c_void_p
        lib.dtf_ps_begin_accept.argtypes = [ctypes.c_void_p]
    lib._ps_bound = True


class ConnectionClosed(OSError):
    """The peer vanished mid-message — retryable, unlike a protocol
    rejection (ValueError), which is deterministic and must fail fast."""


class StaleNativeLib(OSError):
    """libdtf_native.so predates the requested capability — rebuild
    with `make -C dtf_tpu/native`.  Typed so callers can degrade
    loudly without string-matching error messages."""


# Snapshot file format (little-endian), byte-identical between the C++
# and Python stores: 8-byte magic, u64 version, u64 n, f32 params[n],
# f32 velocity[n], then an OPTIONAL footer — 8-byte footer magic + u64
# done_count.  Written atomically (tmp + rename).  The footer carries
# the DONE tally so a PS restart after a worker has delivered DONE and
# exited cannot hang wait(num_workers) one short (ADVICE r5); restore
# accepts footer-less (pre-footer) snapshots with done_count = 0.
SNAP_MAGIC = b"DTFPSNP1"
SNAP_FOOTER_MAGIC = b"DTFPSDN1"

# Restart-generation tag for the snapshot's done_count footer.  The
# done_count persistence exists for a PS-only crash (workers survive,
# reconnect, and their already-delivered DONEs must still count on the
# restarted store).  A WHOLE-JOB supervisor restart is different: every
# worker re-runs from the top and will deliver DONE again, so a
# restored tally from the previous attempt double-counts — the PS
# rank's wait(num_workers) returns early while re-run workers still
# push.  The launch.py supervisor exports DTF_RESTART_GENERATION (its
# attempt counter) to every rank; the snapshot loop tags each dump with
# the generation it was taken under (a sidecar next to the snapshot —
# the snapshot payload itself stays byte-compatible with both store
# builds), and a restore under a NEWER generation strips the done_count
# footer before handing the file to the store: params/velocity/version
# survive, the stale generation's DONE tally does not.
GENERATION_ENV = "DTF_RESTART_GENERATION"


def current_generation() -> int:
    """This process's restart generation (supervisor attempt number);
    0 when unsupervised or on the first attempt."""
    try:
        return int(os.environ.get(GENERATION_ENV, "0"))
    except ValueError:
        return 0


def _generation_sidecar(snap_path: str) -> str:
    return snap_path + ".gen"


def read_snapshot_generation(snap_path: str) -> int:
    """Generation a snapshot was taken under; 0 for pre-generation
    (sidecar-less) snapshots — those predate supervised restarts and
    restore with the legacy semantics."""
    try:
        with open(_generation_sidecar(snap_path)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0


def write_snapshot_generation(snap_path: str, generation: int) -> bool:
    """Atomically record the generation claim.  Returns False on a
    write failure — the caller must then SKIP the snapshot dump: a
    fresh snapshot under a stale sidecar is exactly the state a
    same-generation restore would wrongly strip."""
    path = _generation_sidecar(snap_path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(str(int(generation)))
        os.replace(tmp, path)
    except OSError as e:
        log.warning("PS snapshot generation sidecar write failed: %s", e)
        return False
    return True


def strip_done_footer(snap_path: str) -> bool:
    """Rewrite a snapshot WITHOUT its done_count footer (both stores
    restore footer-less files with the tally at 0).  In place, atomic.
    Returns True when a footer was present and stripped; a malformed
    file is left untouched (restore will quarantine it)."""
    try:
        with open(snap_path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    if len(data) < 24 or data[:8] != SNAP_MAGIC:
        return False
    (n,) = struct.unpack("<Q", data[16:24])
    base = 24 + 8 * n
    if (len(data) != base + 16
            or data[base:base + 8] != SNAP_FOOTER_MAGIC):
        return False
    tmp = f"{snap_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data[:base])
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, snap_path)
    except OSError as e:
        # a write failure (read-only dir, disk full) must not crash the
        # restarting PS rank — restore proceeds with the stale tally,
        # loudly (the lesser evil: early wait() return vs a crash loop)
        log.warning("PS snapshot: could not strip stale done_count "
                    "footer (%s) — restoring WITH the stale tally", e)
        return False
    return True

# Reconnect-reseed guard floor (see PsClient): with fewer than this
# many versions seen, a reconnecting worker may still re-seed an
# uninitialized restarted store — the legitimate pre-first-snapshot
# crash window is ~1 s of cluster pushes (the fast first dump), which
# this bounds generously.  Beyond it the tolerance scales with the
# versions actually seen, so a short run can no longer silently
# discard its whole history just because it stayed under the static
# tolerance (ADVICE r5).
RESEED_ABS_FLOOR = 64

# The ONE copy of the reseed-guard default (config.flags imports it for
# --ps_reseed_tolerance): how many store versions a restarted PS may
# trail what a worker already saw before the worker refuses to
# continue.  Size >= cluster pushes/sec x ps_snapshot_secs + margin.
DEFAULT_RESEED_TOLERANCE = 10_000


class PsServer:
    """The native C++ parameter store (grpc-PS-runtime equivalent).
    Falls back to a pure-Python threaded server when the .so is absent —
    same wire protocol, so clients can't tell."""

    def __init__(self, port: int = 0, momentum: float = 0.9,
                 defer_accept: bool = False):
        """``defer_accept``: bind + listen but queue connections in the
        listen backlog until begin_accept() — the restore-before-serve
        window that keeps a restarted PS's snapshot restore from racing
        early worker INITs."""
        lib = native_lib.load()
        self._native = None
        self._py: Optional[_PyPsServer] = None
        self._accepting = not defer_accept
        if lib is not None and hasattr(lib, "dtf_ps_start"):
            _bind_native(lib)
            if defer_accept and not hasattr(lib, "dtf_ps_start_paused"):
                raise StaleNativeLib(
                    "libdtf_native.so predates deferred accept")
            start = (lib.dtf_ps_start_paused if defer_accept
                     else lib.dtf_ps_start)
            handle = start(port, momentum)
            if not handle:
                raise OSError(f"parameter store: cannot bind port {port}")
            self._native = (lib, handle)
            self.port = lib.dtf_ps_port(handle)
        else:
            self._py = _PyPsServer(port, momentum,
                                   defer_accept=defer_accept)
            self.port = self._py.port
        log.info("parameter store %s on port %d (%s)",
                 "serving" if self._accepting else "bound (paused)",
                 self.port, "native" if self._native else "python")

    @property
    def supports_snapshots(self) -> bool:
        """False only for a stale pre-snapshot libdtf_native.so."""
        if self._native:
            lib, _ = self._native
            return hasattr(lib, "dtf_ps_snapshot")
        return True

    def begin_accept(self) -> None:
        """Start serving queued + future connections (defer_accept)."""
        if self._accepting:
            return
        self._accepting = True
        if self._native:
            lib, handle = self._native
            lib.dtf_ps_begin_accept(handle)
        else:
            self._py.begin_accept()
        log.info("parameter store serving on port %d", self.port)

    def wait(self, n_done: int) -> None:
        """Block until n_done workers reported DONE (or SHUTDOWN)."""
        if self._native:
            lib, handle = self._native
            lib.dtf_ps_wait(handle, n_done)
        else:
            self._py.wait(n_done)

    def snapshot(self, path: str) -> None:
        """Atomic dump of params+velocity+version (the store's whole
        mutable state — the reference's PS held it in memory only and
        told users 'Workers will need to restart training' on a crash,
        ps_server/log1.log).  Raises on failure; a no-op ValueError
        when the store is not yet initialized."""
        if self._native:
            lib, handle = self._native
            if not hasattr(lib, "dtf_ps_snapshot"):
                raise StaleNativeLib(
                    "libdtf_native.so predates PS snapshots")
            rc = lib.dtf_ps_snapshot(handle, path.encode())
            if rc == -1:
                raise ValueError("snapshot: store not initialized")
            if rc != 0:
                raise OSError(f"snapshot to {path!r} failed (rc={rc})")
        else:
            self._py.snapshot(path)

    def restore(self, path: str) -> None:
        """Load a snapshot (marks the store initialized: workers'
        INITs then get already-initialized and pull the restored
        state instead of re-proposing)."""
        if self._native:
            lib, handle = self._native
            if not hasattr(lib, "dtf_ps_restore"):
                raise StaleNativeLib(
                    "libdtf_native.so predates PS snapshots")
            rc = lib.dtf_ps_restore(handle, path.encode())
            if rc == -1:
                raise FileNotFoundError(path)
            if rc != 0:
                raise OSError(f"restore from {path!r} failed: corrupt or "
                              f"truncated snapshot (rc={rc})")
        else:
            self._py.restore(path)

    def stop(self) -> None:
        if self._native:
            lib, handle = self._native
            lib.dtf_ps_stop(handle)
            self._native = None
        elif self._py:
            self._py.stop()
            self._py = None


class _PyPsServer:
    """Protocol-compatible fallback store (used when the C++ library is
    not built; also documents the protocol in Python)."""

    def __init__(self, port: int, momentum: float,
                 defer_accept: bool = False):
        self.momentum = momentum
        self.params: Optional[np.ndarray] = None
        self.velocity: Optional[np.ndarray] = None
        self.version = 0
        self.mu = threading.Lock()
        self.state = threading.Condition()
        self.done_count = 0
        self.stopping = False
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("0.0.0.0", port))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self._threads = []
        self._conns = []
        self._conns_mu = threading.Lock()
        self._accept = threading.Thread(target=self._accept_loop, daemon=True)
        if not defer_accept:
            self._accept.start()

    def begin_accept(self):
        self._accept.start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            with self._conns_mu:
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve(self, conn: socket.socket):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                op = _recvn(conn, 1)
                if not op:
                    return
                op = op[0]
                if op == OP_INIT:
                    (n,) = struct.unpack("<Q", _recvn(conn, 8))
                    if n == 0 or n > MAX_PARAMS:
                        return
                    buf = np.frombuffer(_recvn(conn, 4 * n), np.float32)
                    with self.mu:
                        if self.params is None:
                            self.params = buf.copy()
                            self.velocity = np.zeros_like(self.params)
                            st = 0
                        else:
                            st = 1
                        conn.sendall(struct.pack("<BQQ", st, self.params.size,
                                                 self.version))
                elif op == OP_PULL:
                    with self.mu:
                        if self.params is None:
                            conn.sendall(b"\x02")
                            continue
                        snap = self.params.tobytes()
                        hdr = struct.pack("<BQQ", 0, self.params.size,
                                          self.version)
                    conn.sendall(hdr + snap)
                elif op == OP_PUSH:
                    lr, n = struct.unpack("<fQ", _recvn(conn, 12))
                    if n == 0 or n > MAX_PARAMS:
                        return
                    g = np.frombuffer(_recvn(conn, 4 * n), np.float32)
                    with self.mu:
                        if self.params is None or self.params.size != n:
                            conn.sendall(struct.pack("<BQ", 2, 0))
                            continue
                        self.velocity *= self.momentum
                        self.velocity -= lr * g
                        self.params += self.velocity
                        self.version += 1
                        conn.sendall(struct.pack("<BQ", 0, self.version))
                elif op == OP_PULL16:
                    with self.mu:
                        if self.params is None:
                            conn.sendall(b"\x02")
                            continue
                        snap = _f32_to_bf16_bytes(self.params)
                        hdr = struct.pack("<BQQ", 0, self.params.size,
                                          self.version)
                    conn.sendall(hdr + snap)
                elif op == OP_PUSH16:
                    lr, n = struct.unpack("<fQ", _recvn(conn, 12))
                    if n == 0 or n > MAX_PARAMS:
                        return
                    g = _bf16_bytes_to_f32(_recvn(conn, 2 * n))
                    with self.mu:
                        if self.params is None or self.params.size != n:
                            conn.sendall(struct.pack("<BQ", 2, 0))
                            continue
                        self.velocity *= self.momentum
                        self.velocity -= lr * g
                        self.params += self.velocity
                        self.version += 1
                        conn.sendall(struct.pack("<BQ", 0, self.version))
                elif op == OP_INFO:
                    with self.mu:
                        n = 0 if self.params is None else self.params.size
                        st = 2 if self.params is None else 0
                        conn.sendall(struct.pack("<BQQ", st, n, self.version))
                elif op == OP_DONE:
                    # ack before notifying: wait() returning triggers
                    # stop(), which tears down this connection
                    conn.sendall(b"\x00")
                    with self.state:
                        self.done_count += 1
                        self.state.notify_all()
                elif op == OP_SHUTDOWN:
                    with self.state:
                        self.stopping = True
                        self.state.notify_all()
                    conn.sendall(b"\x00")
                    return
                else:
                    return
        except (OSError, ValueError):
            return
        finally:
            with self._conns_mu:
                if conn in self._conns:
                    self._conns.remove(conn)
            conn.close()

    def wait(self, n_done: int):
        with self.state:
            self.state.wait_for(
                lambda: self.stopping or self.done_count >= n_done)

    def snapshot(self, path: str):
        """Same atomic dump + file format as dtf_ps_snapshot (the C++
        store) — either build restores the other's snapshot.  The
        done_count footer makes the DONE tally restart-durable (a
        crashed PS whose workers already finished must not hang
        wait(num_workers) one short after restore)."""
        # done_count is read BEFORE the params copy: a DONE is only sent
        # after the worker's last push was acked, so any DONE counted
        # here is already reflected in the params we then copy — the
        # reverse order could persist a "done" worker whose final pushes
        # are missing from the saved state
        with self.state:
            done_count = self.done_count
        with self.mu:
            if self.params is None:
                raise ValueError("snapshot: store not initialized")
            params = self.params.copy()
            velocity = self.velocity.copy()
            version = self.version
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(SNAP_MAGIC)
            f.write(struct.pack("<QQ", version, params.size))
            f.write(params.astype("<f4", copy=False).tobytes())
            f.write(velocity.astype("<f4", copy=False).tobytes())
            f.write(SNAP_FOOTER_MAGIC)
            f.write(struct.pack("<Q", done_count))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def restore(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        if len(data) < 24 or data[:8] != SNAP_MAGIC:
            raise OSError(f"restore from {path!r} failed: bad magic")
        version, n = struct.unpack("<QQ", data[8:24])
        base = 24 + 8 * n
        if n == 0 or n > MAX_PARAMS or len(data) not in (base, base + 16):
            raise OSError(f"restore from {path!r} failed: corrupt or "
                          f"truncated snapshot")
        done_count = 0  # footer-less (pre-footer) snapshots restore as 0
        if len(data) == base + 16:
            if data[base:base + 8] != SNAP_FOOTER_MAGIC:
                raise OSError(f"restore from {path!r} failed: corrupt "
                              f"footer")
            (done_count,) = struct.unpack("<Q", data[base + 8:base + 16])
        params = np.frombuffer(data, "<f4", count=n, offset=24).copy()
        velocity = np.frombuffer(data, "<f4", count=n,
                                 offset=24 + 4 * n).copy()
        with self.mu:
            self.params = params
            self.velocity = velocity
            self.version = version
        with self.state:
            self.done_count = int(done_count)
            self.state.notify_all()

    def stop(self):
        """Mirror the native dtf_ps_stop: stop accepting, tear down live
        connections, join serve threads — no push can land after stop."""
        with self.state:
            self.stopping = True
            self.state.notify_all()
        # shutdown() before close(): on Linux a thread blocked in
        # accept() is NOT woken by close() alone
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self._accept.ident is not None:  # may never have started
            self._accept.join(timeout=10)
        with self._conns_mu:
            conns = list(self._conns)
            threads = list(self._threads)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for t in threads:
            t.join(timeout=10)


def _recvn(conn: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = conn.recv(n)
        if not b:
            # OSError subclass: existing (ValueError, OSError) handlers
            # keep working, and PsClient._retrying can distinguish a
            # dead peer (retry) from a protocol rejection (fail fast)
            raise ConnectionClosed("connection closed mid-message")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

class PsClient:
    """Worker-side connection to the parameter store.

    ``reconnect_timeout`` > 0 makes pull/push survive a PS crash (r5,
    VERDICT r4 #4): on a dead connection the client reconnects with
    exponential backoff until the deadline, then retries the whole
    operation against the restarted (snapshot-restored) store.  A push
    that died mid-flight may have already been applied, so a retried
    push can land twice — the usual HogWild/async-SGD consistency
    (duplicate gradient at a stale version), which this mode already
    accepts by design.  0 disables (one failure raises, the pre-r5
    behavior)."""

    # A restarted store may legitimately trail the versions this client
    # saw by up to one snapshot interval of CLUSTER-WIDE pushes (the
    # lost tail).  Beyond the tolerance, the store has effectively LOST
    # the run's state — continuing silently would train a mid-schedule
    # LR against near-initial params, which is scientifically worse
    # than dying.  --ps_reseed_tolerance wires it from the CLI; the
    # default is the shared DEFAULT_RESEED_TOLERANCE.

    def __init__(self, address: str, connect_timeout: float = 60.0,
                 reconnect_timeout: float = 0.0,
                 reseed_tolerance: int = DEFAULT_RESEED_TOLERANCE):
        host, _, port = address.rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        self.reconnect_timeout = reconnect_timeout
        self.reseed_tolerance = reseed_tolerance
        self._init_msg: Optional[bytes] = None
        self._last_version = 0  # highest store version this client saw
        # one-off ad-hoc counters absorbed into the obs registry: the
        # push/pull/reconnect tallies live behind the same API (and
        # BenchmarkMetric export) as every other subsystem's metrics
        reg = default_registry()
        self._m_pulls = reg.counter("ps_client_pulls", unit="ops")
        self._m_pushes = reg.counter("ps_client_pushes", unit="ops")
        self._m_reconnects = reg.counter("ps_client_reconnects", unit="ops")
        self._m_pull_bytes = reg.counter("ps_client_pull_bytes", unit="bytes")
        self._m_push_bytes = reg.counter("ps_client_push_bytes", unit="bytes")
        self._connect(connect_timeout)

    def _chaos_drop(self) -> None:
        """ps_drop@version:N probe: once the observed store version
        reaches N, sever this client's connection (one-shot) — the next
        op fails with OSError and exercises the real reconnect+backoff
        machinery, not a mock of it."""
        if chaos.ps_drop(self._last_version):
            try:
                self.sock.close()
            except OSError:
                pass

    def _connect(self, timeout: float):
        deadline = time.time() + timeout
        delay = 0.2
        while True:
            try:
                self.sock = socket.create_connection(self.address, timeout=300)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(delay)  # PS rank may still be starting
                delay = min(delay * 1.5, 5.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _retrying(self, op_name: str, fn):
        """Runs fn(); on a DEAD CONNECTION (OSError, incl. the
        ConnectionClosed that _recvn raises mid-message), reconnects
        with backoff and retries until reconnect_timeout is spent.
        Protocol rejections (ValueError) are deterministic — they
        propagate immediately."""
        if not self.reconnect_timeout:
            return fn()
        deadline = time.time() + self.reconnect_timeout
        while True:
            try:
                return fn()
            except OSError:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise
                log.warning("ps %s failed; reconnecting to %s "
                            "(%.0fs left)", op_name, self.address,
                            remaining)
                self._m_reconnects.inc()
                trace.event("ps_reconnect", op=op_name,
                            address=f"{self.address[0]}:{self.address[1]}")
                try:
                    self.sock.close()
                except OSError:
                    pass
                self._connect(remaining)
                # re-propose our init against the restarted store:
                # idempotent (first-wins) — it loses (st=1) against a
                # snapshot-restored store, but re-seeds a store that
                # restarted with NO snapshot (the pre-first-dump crash
                # window), so workers stay alive instead of fail-fast
                # dying on status-2 pushes.  GUARDED against the silent
                # step-0 reset (r5 high-effort review): if this client
                # has already seen a version far beyond what a lost
                # snapshot tail explains, the restarted store has LOST
                # the run — die loudly rather than continue a
                # mid-schedule run against near-initial params.
                if self._init_msg is not None and op_name != "init":
                    try:
                        # probe with the NON-MUTATING INFO first: a
                        # store that lost the run must be refused
                        # WITHOUT seeding it (a seeded lost store would
                        # look plausibly-initialized to a freshly
                        # restarted worker and resurrect the silent
                        # step-0 reset this guard exists to prevent)
                        self.sock.sendall(bytes([OP_INFO]))
                        st, _, ver = struct.unpack(
                            "<BQQ", _recvn(self.sock, 17))
                        lost = self._last_version - ver
                        # effective tolerance scales with the history
                        # this client actually saw: a short run (total
                        # pushes far under the static tolerance) must
                        # not silently discard its entire progress just
                        # because the loss fits the 10k default — only
                        # losses plausible for the pre-first-snapshot
                        # window (RESEED_ABS_FLOOR) or a bounded
                        # fraction of the seen history pass
                        effective = min(
                            self.reseed_tolerance,
                            max(RESEED_ABS_FLOOR, self._last_version // 2))
                        if lost > effective:
                            raise RuntimeError(
                                f"restarted parameter store is at "
                                f"version {ver} but this worker already "
                                f"saw {self._last_version} (effective "
                                f"reseed tolerance {effective}) — the "
                                f"store lost the run's state (missing/"
                                f"corrupt snapshot?).  Refusing to "
                                f"continue mid-schedule from "
                                f"near-initial params; restart the job")
                        if st == 2:
                            # uninitialized AND within tolerance: the
                            # pre-first-dump crash window — re-seed
                            if self._last_version > 0:
                                log.error(
                                    "ps reconnect: re-seeding a "
                                    "restarted store from init params "
                                    "(last seen version %d) — the "
                                    "pre-snapshot crash window",
                                    self._last_version)
                            self.sock.sendall(self._init_msg)
                            _recvn(self.sock, 17)
                    except (OSError, ValueError):
                        # the socket may still be alive but DESYNCED
                        # (late INIT reply bytes would be parsed as the
                        # next op's response) — close it so the next
                        # iteration's failure path truly reconnects
                        try:
                            self.sock.close()
                        except OSError:
                            pass
                        continue

    def init(self, params: np.ndarray) -> Tuple[int, int]:
        """Propose initial params; first worker wins (the
        BroadcastGlobalVariablesCallback(0) equivalent).  Returns
        (status, version).  Under reconnect_timeout a crash during
        startup retries like pull/push — a re-sent INIT is idempotent
        (it wins at most once)."""
        params = np.ascontiguousarray(params, np.float32)
        msg = (bytes([OP_INIT]) + struct.pack("<Q", params.size) +
               params.tobytes())
        if self.reconnect_timeout:
            # replayed on reconnect (see _retrying); without reconnect
            # the replay is unreachable — don't pin ~4·N bytes forever
            self._init_msg = msg

        def once():
            self.sock.sendall(msg)
            st, n, ver = struct.unpack("<BQQ", _recvn(self.sock, 17))
            if st not in (0, 1) or n != params.size:
                raise ValueError(f"ps init rejected: status={st} size={n}")
            self._last_version = max(self._last_version, ver)
            return st, ver

        return self._retrying("init", once)

    def pull(self, retry_interval: float = 0.1, timeout: float = 120.0,
             bf16: bool = False) -> Tuple[int, np.ndarray]:
        """Returns (version, flat f32 params); blocks until initialized.
        ``bf16`` pulls the bfloat16 wire encoding (half the traffic);
        the returned array is expanded back to f32."""
        deadline = time.time() + timeout

        def once():
            self.sock.sendall(bytes([OP_PULL16 if bf16 else OP_PULL]))
            (st,) = _recvn(self.sock, 1)
            if st == 0:
                n, ver = struct.unpack("<QQ", _recvn(self.sock, 16))
                if bf16:
                    flat = _bf16_bytes_to_f32(_recvn(self.sock, 2 * n))
                else:
                    flat = np.frombuffer(_recvn(self.sock, 4 * n),
                                         np.float32)
                self._last_version = max(self._last_version, ver)
                self._m_pulls.inc()
                self._m_pull_bytes.inc((2 if bf16 else 4) * int(n))
                self._chaos_drop()
                return ver, flat
            return None

        while True:
            with trace.span("ps_pull", bf16=bf16):
                got = self._retrying("pull", once)
            if got is not None:
                return got
            if time.time() > deadline:
                raise TimeoutError("parameter store never initialized")
            time.sleep(retry_interval)

    def push(self, lr: float, grads: np.ndarray, bf16: bool = False) -> int:
        """Apply one async Keras-SGD step on the store; returns the new
        version.  ``bf16`` sends gradients as bfloat16 on the wire (the
        store's update math stays f32)."""
        grads = np.ascontiguousarray(grads, np.float32)
        if bf16:
            msg = (bytes([OP_PUSH16]) +
                   struct.pack("<fQ", float(lr), grads.size) +
                   _f32_to_bf16_bytes(grads))
        else:
            msg = (bytes([OP_PUSH]) +
                   struct.pack("<fQ", float(lr), grads.size) +
                   grads.tobytes())

        def once():
            self.sock.sendall(msg)
            st, ver = struct.unpack("<BQ", _recvn(self.sock, 9))
            if st != 0:
                raise ValueError(f"ps push rejected: status={st}")
            self._last_version = max(self._last_version, ver)
            self._m_pushes.inc()
            self._m_push_bytes.inc(len(msg))
            self._chaos_drop()
            return ver

        with trace.span("ps_push", bf16=bf16):
            return self._retrying("push", once)

    def info(self) -> Tuple[int, int, int]:
        def once():
            self.sock.sendall(bytes([OP_INFO]))
            st, n, ver = struct.unpack("<BQQ", _recvn(self.sock, 17))
            # keep the reconnect reseed guard's baseline fresh: a client
            # whose latest traffic was info() must not under-detect a
            # store that lost the run (ADVICE r5)
            self._last_version = max(self._last_version, ver)
            return st, n, ver

        return self._retrying("info", once)

    def done(self) -> None:
        """DONE rides the reconnect machinery too (r5 high-effort
        review): a worker finishing while the PS is down must deliver
        its DONE to the RESTARTED store, or the PS rank's
        wait(num_workers) hangs forever one short.

        The delivery cannot naively retry the DONE itself: a lost ACK
        is indistinguishable from a lost DONE, and the store may
        legitimately tear down the moment the last DONE lands (ack
        loss is normal there).  So liveness is verified FIRST with a
        retried INFO round-trip — reconnecting to a restarted store if
        needed — and the DONE then goes out on that just-verified
        connection with ack loss tolerated, exactly the pre-r5
        semantics on a connection now known to be good."""
        try:
            self._retrying("info", lambda: (
                self.sock.sendall(bytes([OP_INFO])),
                _recvn(self.sock, 17)))
            self.sock.sendall(bytes([OP_DONE]))
        except (ValueError, OSError, RuntimeError) as e:
            # best-effort: never fail a FINISHED worker on DONE — even
            # the reseed guard's lost-store refusal is moot here, the
            # work is already complete.  But say so: an undelivered
            # DONE leaves the PS rank's wait(num_workers) hanging, and
            # this line is the only diagnostic of which worker and why.
            log.warning("ps done() not delivered (%s: %s) — the PS "
                        "rank's wait() will be one DONE short",
                        type(e).__name__, e)
            return
        try:
            _recvn(self.sock, 1)
        except (ValueError, OSError):
            # the store may tear down as soon as the last DONE lands;
            # losing the ack is fine — the DONE itself was delivered
            pass

    def shutdown_server(self) -> None:
        self.sock.sendall(bytes([OP_SHUTDOWN]))
        _recvn(self.sock, 1)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# The async training entry (role dispatch)
# ---------------------------------------------------------------------------

class _SnapshotLoop:
    """PS-rank periodic snapshotter: restore-at-start + a background
    thread dumping the store every interval + a final dump at stop.
    The snapshot path is stable (<dir>/ps_store.snap) and each write is
    atomic, so a restarted PS always finds the newest complete state.

    Construct with the server still in defer_accept — the restore runs
    before any worker INIT is served, then the caller begin_accept()s.
    A corrupt snapshot is quarantined (renamed .corrupt) and logged,
    never crash-looped on: serving fresh state with a loud error beats
    a PS that can't start at all."""

    def __init__(self, server: PsServer, snap_dir: str, interval: float):
        self.server = server
        self.path = os.path.join(snap_dir, "ps_store.snap")
        self.interval = max(interval, 0.5)
        self._stop = threading.Event()
        os.makedirs(snap_dir, exist_ok=True)
        if not server.supports_snapshots:
            # stale .so: degrade loudly — a good snapshot must NOT be
            # quarantined just because this build can't read it
            log.error("PS rank: libdtf_native.so predates snapshots — "
                      "--ps_snapshot_dir disabled (rebuild with "
                      "`make -C dtf_tpu/native`)")
            self._thread = None
            return
        if os.path.exists(self.path):
            gen, snap_gen = current_generation(), \
                read_snapshot_generation(self.path)
            if snap_gen != gen and strip_done_footer(self.path):
                # whole-job restart (new supervisor attempt): the
                # persisted DONE tally belongs to workers of the STALE
                # generation — they re-run and re-deliver; counting the
                # old tally would double-count and let wait(num_workers)
                # return early.  Params/velocity/version still restore.
                log.warning(
                    "PS rank: snapshot done_count is from restart "
                    "generation %d (this attempt is generation %d) — "
                    "discarded; re-run workers re-deliver their DONEs",
                    snap_gen, gen)
            try:
                server.restore(self.path)
                log.info("PS rank: restored snapshot %s (generation %d)",
                         self.path, gen)
            except OSError as e:
                quarantine = self.path + ".corrupt"
                log.error("PS rank: snapshot %s unusable (%s) — moved "
                          "to %s, serving fresh state", self.path, e,
                          quarantine)
                try:
                    os.replace(self.path, quarantine)
                except OSError:
                    pass
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        # poll fast only while the store is UNINITIALIZED (so the first
        # dump lands within ~1 s of the first worker INIT — a crash in
        # the initial ps_snapshot_secs window must not restart into an
        # empty store with no snapshot at all).  I/O failures back off
        # to the normal interval: a full disk must not warn at 1 Hz for
        # the rest of training.
        state = "uninit"
        while True:
            delay = (min(1.0, self.interval) if state == "uninit"
                     else self.interval)
            if self._stop.wait(delay):
                return
            state = self._snap()

    def _snap(self) -> str:
        """"saved" | "uninit" | "ioerror" (logged)."""
        try:
            # sidecar FIRST: a crash between the two writes must never
            # leave a new snapshot under-claimed by an old sidecar — a
            # same-generation restore would then strip a legitimate
            # done_count and wait(num_workers) would hang.  The inverse
            # window (new sidecar + old snapshot) is safe: any stale-
            # generation footer was already stripped in place at this
            # loop's restore, so an on-disk footer is always ours.  A
            # FAILED sidecar write skips the dump for the same reason —
            # dumping anyway would recreate the old-sidecar/new-
            # snapshot state the ordering exists to prevent.
            if not write_snapshot_generation(self.path,
                                             current_generation()):
                return "ioerror"
            self.server.snapshot(self.path)
            return "saved"
        except ValueError:
            return "uninit"  # not initialized yet — nothing to save
        except OSError as e:
            log.warning("PS snapshot failed: %s", e)
            return "ioerror"

    def stop(self):
        if self._thread is None:  # snapshots disabled (stale .so)
            return
        self._stop.set()
        self._thread.join(timeout=10)
        self._snap()  # final state, so a clean stop loses nothing


def _serve_with_snapshots(cfg, port: int):
    """PS-rank store construction with the fault-tolerance ordering:
    bind paused → restore the snapshot (no worker INIT can race it;
    early connects just queue in the listen backlog) → begin accepting.
    Without --ps_snapshot_dir this is a plain immediately-serving
    store."""
    if not cfg.ps_snapshot_dir:
        return PsServer(port=port), None
    try:
        server = PsServer(port=port, defer_accept=True)
    except StaleNativeLib as e:
        # stale .so can't pause-accept OR snapshot: degrade loudly to
        # the plain reference-grade in-memory store
        log.error("PS rank: %s — --ps_snapshot_dir disabled", e)
        return PsServer(port=port), None
    snap = _SnapshotLoop(server, cfg.ps_snapshot_dir, cfg.ps_snapshot_secs)
    server.begin_accept()
    return server, snap


def run_async(cfg) -> dict:
    """Async-PS run: process 0 serves, 1..N train independently.

    With no multi-process topology configured, runs a self-contained
    single-process demo: in-process store + one worker loop (the
    easiest way to see the async mode work, and what the tests drive).
    """
    n_procs = cfg.process_count or 1
    if n_procs <= 1:
        server, snap = _serve_with_snapshots(cfg, port=0)
        try:
            return _worker(cfg, f"127.0.0.1:{server.port}", worker_id=0,
                           num_workers=1)
        finally:
            if snap:
                snap.stop()
            server.stop()

    if not cfg.coordinator_address or cfg.process_id is None:
        raise ValueError("async parameter_server needs coordinator_address "
                         "and process_id (the PS address doubles as the "
                         "coordinator)")
    num_workers = n_procs - 1
    if cfg.process_id == 0:
        from dtf_tpu.train import preemption
        port = int(cfg.coordinator_address.rpartition(":")[2])
        server, snap = _serve_with_snapshots(cfg, port=port)
        log.info("PS rank: serving %d workers", num_workers)
        try:
            # blocks like the reference PS rank, but exits when all
            # workers finish — AND polls for preemption: preempted
            # workers deliberately skip their DONE (progress lives in
            # the store snapshot), so wait(num_workers) would never
            # return on a pod-wide SIGTERM; the PS rank must notice its
            # own latched signal, dump a final snapshot, and exit 75
            # with everyone else instead of hanging until SIGKILL
            # (which would classify as a crash and burn restart budget)
            waiter = threading.Thread(target=server.wait,
                                      args=(num_workers,), daemon=True)
            waiter.start()
            while waiter.is_alive():
                waiter.join(timeout=0.5)
                signum = preemption.triggered()
                if signum is not None:
                    raise preemption.Preempted(0, signum)
        finally:
            if snap:
                snap.stop()  # final dump: a clean OR preempted stop
            server.stop()    # loses nothing
        return {}
    return _worker(cfg, cfg.coordinator_address,
                   worker_id=cfg.process_id - 1, num_workers=num_workers)


def _worker(cfg, ps_address: str, worker_id: int, num_workers: int) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from dtf_tpu.data import get_dataset_spec, synthetic_input_fn
    from dtf_tpu.models import build_model
    from dtf_tpu.models.registry import l2_weight_penalty
    from dtf_tpu.train import schedules as sched_lib
    from dtf_tpu.train.loop import cross_entropy
    from dtf_tpu.utils.logs import TimeHistory, build_stats

    spec = get_dataset_spec(cfg.dataset)
    if cfg.num_classes:
        import dataclasses
        spec = dataclasses.replace(spec, num_classes=cfg.num_classes)

    if cfg.stop_threshold is not None and worker_id == 0:
        log.warning("--stop_threshold is ignored in async PS mode: workers "
                    "evaluate once after their step budget, not per epoch")

    batch = cfg.batch_size  # per-worker, like the reference's --batch_size 192
    model_name = "trivial" if cfg.use_trivial_model else cfg.model
    if model_name.startswith(("moe_transformer", "pipeline_transformer")):
        # the async loop applies models without the aux_loss collection
        # and without mesh axes; routed/pipelined families need the SPMD
        # path (and make little sense against a central param store)
        raise ValueError(
            f"model {model_name!r} is not supported in async "
            "parameter-server mode; use --ps_mode sync (the SPMD "
            "reinterpretation) for MoE/pipeline families")
    if cfg.shard_lm_head or cfg.model_parallelism > 1 or cfg.seq_parallelism > 1:
        # no mesh in the async loop — a silently-dense head or an unused
        # parallel axis would contradict what the flags promise
        raise ValueError(
            "--shard_lm_head/--model_parallelism/--seq_parallelism need "
            "the SPMD path; async parameter-server workers are "
            "single-device")
    if cfg.eval_only or cfg.clip_grad_norm or cfg.optimizer_sharding:
        raise ValueError(
            "--eval_only/--clip_grad_norm/--optimizer_sharding are not "
            "implemented for async parameter-server mode; use "
            "--ps_mode sync")
    model, l2w = build_model(model_name, num_classes=spec.num_classes,
                             dtype=cfg.compute_dtype)

    # steps_per_epoch = train_steps // num_workers (ps_0.py:263 semantics)
    full_steps = max(spec.num_train // batch, 1)
    steps_per_epoch = max(full_steps // num_workers, 1)
    train_epochs = cfg.train_epochs
    if cfg.train_steps:
        steps_per_epoch = min(cfg.train_steps, steps_per_epoch)
        train_epochs = 1
    # The reference's LR callback follows the *keras epoch counter*
    # (common.py LearningRateBatchScheduler uses on_epoch_begin's epoch),
    # and each PS worker's epoch is steps//num_workers long — so the
    # schedule must be built on the per-worker epoch length for decay
    # boundaries to land on the same epoch numbers.
    schedule = sched_lib.for_dataset(spec.name, batch, steps_per_epoch,
                                     spec.num_train,
                                     use_tensor_lr=cfg.use_tensor_lr)

    if cfg.use_synthetic_data or not cfg.data_dir:
        train_iter = synthetic_input_fn(spec, True, batch,
                                        cfg.seed + worker_id)
        eval_iter_fn = lambda: synthetic_input_fn(spec, False, batch,
                                                  cfg.seed + 10_000)
    elif spec.name == "cifar10":
        from dtf_tpu.data.cifar import cifar_input_fn
        train_iter = cifar_input_fn(cfg.data_dir, True, batch, seed=cfg.seed,
                                    process_id=worker_id,
                                    process_count=num_workers,
                                    wire=cfg.input_wire)
        eval_iter_fn = lambda: cifar_input_fn(cfg.data_dir, False, batch,
                                              wire=cfg.input_wire)
    else:
        from dtf_tpu.data.imagenet import imagenet_input_fn
        train_iter = imagenet_input_fn(cfg.data_dir, True, batch,
                                       seed=cfg.seed, process_id=worker_id,
                                       process_count=num_workers,
                                       wire=cfg.input_wire)
        eval_iter_fn = lambda: imagenet_input_fn(cfg.data_dir, False, batch,
                                                 wire=cfg.input_wire)
    # uint8 wire: normalization runs inside the jitted step (same
    # single-source decision as the SPMD runner)
    from dtf_tpu.data import normalize as normalize_lib
    norm_fn = normalize_lib.for_config(cfg, spec)

    first_batch = next(train_iter)
    train_iter = itertools.chain([first_batch], train_iter)  # keep batch 0
    init_images = jnp.asarray(first_batch[0][:1])
    if norm_fn is not None:
        init_images = norm_fn(init_images)
    variables = jax.jit(model.init, static_argnames=("train",))(
        jax.random.key(cfg.seed), init_images, train=False)
    params0 = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    flat0, unravel = ravel_pytree(params0)

    # with snapshots configured, workers outlive a PS crash: reconnect
    # with backoff (--ps_reconnect_secs) and resume against the
    # restored store
    client = PsClient(ps_address,
                      reconnect_timeout=cfg.ps_reconnect_secs
                      if cfg.ps_snapshot_dir else 0.0,
                      reseed_tolerance=cfg.ps_reseed_tolerance)
    st, _ = client.init(np.asarray(jax.device_get(flat0), np.float32))
    log.info("worker %d/%d: params %d floats (%s init)", worker_id,
             num_workers, flat0.size, "won" if st == 0 else "lost")

    has_bn = bool(batch_stats)

    @jax.jit
    def step_fn(flat_params, batch_stats, images, labels):
        if norm_fn is not None:
            images = norm_fn(images)
        params = unravel(flat_params)

        def loss_fn(p):
            variables = {"params": p}
            if has_bn:
                variables["batch_stats"] = batch_stats
                logits, mut = model.apply(variables, images, train=True,
                                          mutable=["batch_stats"])
                new_stats = mut["batch_stats"]
            else:
                logits = model.apply(variables, images, train=True)
                new_stats = batch_stats
            loss = cross_entropy(logits, labels) + l2_weight_penalty(p, l2w)
            return loss, (logits, new_stats)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        gflat, _ = ravel_pytree(grads)
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return gflat.astype(jnp.float32), loss, acc, new_stats

    @jax.jit
    def eval_fn(flat_params, batch_stats, images, labels):
        if norm_fn is not None:
            images = norm_fn(images)
        params = unravel(flat_params)
        variables = {"params": params}
        if has_bn:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, images, train=False)
        loss = cross_entropy(logits, labels)
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, acc

    wire_bf16 = cfg.ps_wire == "bf16"
    time_cb = TimeHistory(batch, cfg.log_steps)
    acc_key = ("categorical_accuracy" if spec.one_hot
               else "sparse_categorical_accuracy")
    history: dict = {"loss": [], acc_key: []}
    # same watchdog surface as the SPMD loop: NaN guard on the loss
    # values this loop already syncs, heartbeat when launched under the
    # supervisor (a PS worker that deadlocks in pull() stops beating)
    from dtf_tpu.obs.watchdog import Heartbeat, NanLossWatchdog
    from dtf_tpu.train import preemption
    nan_guard = NanLossWatchdog(enabled=getattr(cfg, "nan_guard", True))
    heartbeat = Heartbeat.from_env(
        interval_s=getattr(cfg, "heartbeat_secs", 5.0))
    time_cb.on_train_begin()
    local_step = 0
    preempted = False
    # the whole worker body runs under a DONE guarantee: a NaN-guard
    # abort (or any other worker death past init) must still deliver
    # this worker's DONE, or the PS rank's wait(num_workers) hangs one
    # short forever — the exact barrier the done_count persistence
    # machinery exists to protect
    try:
        for epoch in range(train_epochs):
            time_cb.on_epoch_begin(epoch)
            for _ in range(steps_per_epoch):
                time_cb.on_batch_begin(local_step)
                version, flat = client.pull(bf16=wire_bf16)
                images, labels = next(train_iter)
                # the per-step device_get below syncs every step in
                # this loop anyway, so keeping it INSIDE the span makes
                # the span a true step time (unlike the SPMD loop's
                # async-dispatch step spans)
                with trace.span("step", step=local_step, worker=worker_id):
                    gflat, loss, acc, batch_stats = step_fn(
                        jnp.asarray(flat), batch_stats, jnp.asarray(images),
                        jnp.asarray(labels))
                    gnp = np.asarray(jax.device_get(gflat))
                # ASYNC NETWORK BOUNDARY: push to the store; other workers
                # may have advanced `version` meanwhile (stale gradients are
                # inherent to async PS — same as the reference)
                lr = float(schedule(jnp.asarray(local_step)))
                client.push(lr, gnp, bf16=wire_bf16)
                local_step += 1
                time_cb.on_batch_end(local_step)
                if heartbeat is not None:
                    heartbeat.beat(step=local_step)
                # chaos step probe (crash@step / sigterm@step fire here
                # for PS workers too) + cooperative preemption: the
                # store already holds every pushed gradient, so a
                # preempted worker just exits EXIT_PREEMPTED — progress
                # lives in the PS snapshot, not a local checkpoint
                chaos.step(local_step)
                signum = preemption.triggered()
                if signum is not None:
                    raise preemption.Preempted(local_step, signum)
            m_loss, m_acc = (float(jax.device_get(loss)),
                             float(jax.device_get(acc)))
            nan_guard.check(local_step, m_loss)
            history["loss"].append(m_loss)
            history[acc_key].append(m_acc)
            time_cb.on_epoch_end(epoch)
            log.info("worker %d epoch %d/%d: loss=%.4f top1=%.4f", worker_id,
                     epoch + 1, train_epochs, m_loss, m_acc)
        time_cb.on_train_end()

        eval_output = None
        if not cfg.skip_eval and worker_id == 0:
            _, flat = client.pull()
            losses, accs = [], []
            for images, labels in eval_iter_fn():
                l, a = eval_fn(jnp.asarray(flat), batch_stats,
                               jnp.asarray(images), jnp.asarray(labels))
                losses.append(float(l))
                accs.append(float(a))
            if losses:
                eval_output = (float(np.mean(losses)), float(np.mean(accs)))
                log.info("worker 0 eval: loss=%.4f top1=%.4f", *eval_output)

        stats = build_stats(history, eval_output, time_cb)
        if worker_id == 0:
            if cfg.export_dir:
                # --export_dir: final store params + this worker's BN stats
                import types
                from dtf_tpu.train.checkpoint import export_model
                _, flat = client.pull()
                export_model(cfg.export_dir, types.SimpleNamespace(
                    params=unravel(jnp.asarray(flat)),
                    batch_stats=batch_stats))
            if cfg.benchmark_log_dir:
                from dtf_tpu.utils.benchmark_logger import BenchmarkFileLogger
                blog = BenchmarkFileLogger(cfg.benchmark_log_dir)
                blog.log_run_info(cfg.model, cfg.dataset, cfg.to_dict(),
                                  test_id=cfg.benchmark_test_id)
                blog.log_stats(stats, global_step=local_step)
                # PS wire counters (pulls/pushes/bytes/reconnects) ride
                # the same metric.log the training stats land in
                blog.log_registry(default_registry(), global_step=local_step)
    except preemption.Preempted:
        # preempted, NOT finished: the supervisor restarts the whole
        # job, and this worker will run again — delivering DONE now
        # would poison the (snapshot-persisted) done_count and let the
        # restarted PS rank's wait(num_workers) return early
        preempted = True
        raise
    except BaseException:
        # dying worker: still deliver DONE (the finally below), but
        # best-effort FAST — done()'s retried INFO probe must not burn
        # another full reconnect_timeout against a store that may be
        # the very thing that just failed
        client.reconnect_timeout = min(client.reconnect_timeout or 0.0, 5.0)
        raise
    finally:
        try:
            if not preempted:
                client.done()  # swallows delivery failures (logs warning)
        finally:
            client.close()
    log.info("Run stats: %s",
             {k: v for k, v in stats.items() if k != "step_timestamp_log"})
    return stats
