"""Named collectives over mesh axes — the communication backend.

Concept map from the reference (SURVEY.md §5.8, §2.4): every primitive
here lowers to an XLA collective that rides ICI within a slice and DCN
across slices; there is no first-party wire protocol to maintain, which
is the point of the TPU-native design.

  reference mechanism                     → here
  -------------------------------------------------------------------
  NCCL ring allreduce (Horovod)           → all_reduce_sum/mean (psum/pmean)
  collective allreduce (--all_reduce_alg) → same; algorithm choice is
                                            XLA's (latency-optimal on ICI)
  hvd.BroadcastGlobalVariablesCallback(0) → broadcast_from(root=0)
  grpc PS push/pull (async)               → parallel.ps (C++ store); the
                                            sync SPMD reinterpretation
                                            needs only psum
  MPI rank / size                         → axis_index / axis_size

All functions must be called inside a `shard_map`ped (or otherwise
axis-bound) computation.
"""

from __future__ import annotations

import functools

import jax
from jax import lax


def axis_size(axis_name: str):
    """Number of shards along a mesh axis (hvd.size equivalent)."""
    return lax.psum(1, axis_name)


def axis_index(axis_name: str):
    """This shard's position along a mesh axis (hvd.rank equivalent)."""
    return lax.axis_index(axis_name)


def all_reduce_sum(x, axis_name: str):
    return jax.tree_util.tree_map(lambda a: lax.psum(a, axis_name), x)


def all_reduce_mean(x, axis_name: str):
    return jax.tree_util.tree_map(lambda a: lax.pmean(a, axis_name), x)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.tree_util.tree_map(
        lambda a: lax.all_gather(a, axis_name, axis=axis, tiled=tiled), x)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return jax.tree_util.tree_map(
        lambda a: lax.psum_scatter(a, axis_name, scatter_dimension=axis,
                                   tiled=True), x)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the axis ring: shard i → shard (i+shift)%n.

    The building block of ring attention (ppermute over ICI neighbors,
    which XLA overlaps with compute).
    """
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree_util.tree_map(
        lambda a: lax.ppermute(a, axis_name, perm), x)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region(x, axis_name: str):
    """Megatron's `f` operator — enter a tensor-parallel region.

    Forward: identity.  Backward: all-reduce the cotangent over the
    tensor-parallel axis.  Needed because a column-parallel layer's
    input cotangent is partial (each shard back-propagates only its
    slice of the weight); without the psum every parameter *upstream*
    of the TP region (LayerNorm, embeddings) would get wrong gradients.
    The matching exit operator is `tp_psum` below (sum forward,
    identity backward — the row-parallel output semantics).  NOT a raw
    `lax.psum`: see tp_psum's docstring for why."""
    return x


def _tp_region_fwd(x, axis_name):
    return x, None


def _tp_region_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


tp_region.defvjp(_tp_region_fwd, _tp_region_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_psum(x, axis_name: str):
    """Megatron's `g` operator — exit a tensor-parallel region.

    Forward: all-reduce (sum) the shards' partial results.  Backward:
    identity.  A *raw* ``lax.psum`` must not be used here: under
    shard_map AD the transpose of psum is psum (the true transpose of
    the joint program, in which every shard carries an identical loss
    replica), so each raw psum on the value path multiplies the
    upstream cotangent by the axis size — compounding per layer.  The
    single correct gradient of *one* loss replica needs identity
    backward, which is exactly Megatron's g."""
    return lax.psum(x, axis_name)


def _tp_psum_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _tp_psum_bwd(axis_name, _, g):
    return (g,)


tp_psum.defvjp(_tp_psum_fwd, _tp_psum_bwd)


def broadcast_from(x, axis_name: str, root: int = 0):
    """One-to-all broadcast along an axis (hvd broadcast equivalent)."""
    idx = lax.axis_index(axis_name)

    def bc(a):
        masked = jax.numpy.where(idx == root, a, jax.numpy.zeros_like(a))
        return lax.psum(masked, axis_name)

    return jax.tree_util.tree_map(bc, x)
