"""GPipe-style pipeline parallelism over a mesh axis.

No reference counterpart (SURVEY.md §2.2 lists PP as an explicit
absence); built TPU-first: the schedule is a single ``lax.scan`` whose
body computes one stage tick and rotates activations to the clockwise
neighbor with ``lax.ppermute`` — an ICI neighbor exchange XLA overlaps
with the next tick's compute.  Running inside ``shard_map`` keeps the
whole pipeline one SPMD program: reverse-mode AD of the scan+ppermute
program *is* the backward pipeline schedule, so no hand-written
backward pass exists.

Semantics: classic GPipe.  ``num_microbatches`` activations flow
through ``pp`` stages in ``num_microbatches + pp - 1`` ticks; the
pipeline bubble is the usual (pp-1)/(M+pp-1) fraction, amortized by
choosing M ≥ pp.  Bubble ticks still execute the stage computation on
placeholder data (XLA needs static control flow — SURVEY's "no
data-dependent Python control flow under jit" rule); their results are
masked out of the output buffer and receive zero cotangents.

The runner auto-scales M to 4·pp when --num_microbatches is unset
(halving to divide the per-shard batch).  Measured at pp=4 on the
8-device CPU mesh, same global batch (bench_lm.py --variant gpipe):
M=4 → M=16 is 1.56× step time — the bubble+placeholder-compute
fraction goes from (7-4)/7 = 43% of ticks to (19-16)/19 = 16%.
`pipeline_spmd_interleaved` (below) instead halves the bubble TIME at
equal M: measured 1.45× at M=pp and 1.12× at M=4·pp.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(stage_fn, x_microbatches, axis_name: str):
    """Run ``stage_fn`` as one pipeline stage per shard of ``axis_name``.

    Must be called inside shard_map with ``axis_name`` bound.

    stage_fn: activation -> activation, shape-preserving (this shard's
      stack of layers).
    x_microbatches: [M, microbatch, ...] — the microbatched input,
      replicated over ``axis_name`` (only stage 0 reads it).

    Returns [M, microbatch, ...] outputs — valid on the LAST stage,
    zeros elsewhere; mask-psum over ``axis_name`` to broadcast.
    """
    pp = lax.psum(1, axis_name)          # static axis size
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    perm = [(j, (j + 1) % pp) for j in range(pp)]

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (clamped reads during drain ticks
        # are discarded downstream); later stages consume the neighbor's
        # activation from the previous tick
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), keepdims=False)
        out = stage_fn(jnp.where(idx == 0, mb, recv))
        # the last stage finishes microbatch t-(pp-1) at tick t
        w = jnp.clip(t - (pp - 1), 0, m - 1)
        valid = jnp.logical_and(idx == pp - 1, t >= pp - 1)
        cur = lax.dynamic_index_in_dim(outputs, w, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, out, cur), w, axis=0)
        # NETWORK BOUNDARY: activation handoff to the next stage
        recv = lax.ppermute(out, axis_name, perm)
        return (recv, outputs), None

    carry0 = (jnp.zeros_like(x_microbatches[0]),
              jnp.zeros_like(x_microbatches))
    (_, outputs), _ = lax.scan(tick, carry0, jnp.arange(m + pp - 1))
    return outputs


def pipeline_spmd_interleaved(stage_fn, x_microbatches, axis_name: str):
    """Two-virtual-stages-per-device (interleaved) GPipe schedule.

    Device j runs virtual stages j and j+pp: a microbatch circles the
    ring twice, using the device's first layer chunk on lap 0 and its
    second on lap 1.  Each tick runs HALF a stage's layers, and the
    schedule takes 2·pp·ceil(M/pp) + pp - 1 ticks (= 2M + pp - 1 when
    pp | M) — so the fill/drain bubble costs (pp-1) half-ticks instead
    of GPipe's (pp-1) full ticks: bubble time halves at equal M
    (Megatron-LM interleaving, arXiv:2104.04473 §2.2, expressed in the
    same scan+ppermute SPMD formulation as `pipeline_spmd`).

    The static injection pattern alternates pp-tick blocks: device 0
    injects microbatches m = b·pp + r at tick i = 2·pp·b + r, and the
    lap-1 activation of that microbatch returns to device 0 exactly pp
    ticks later, in the non-injection block.  Chunk selection at
    (device j, tick t) is the parity of (t - j) // pp — fully static,
    no data-dependent control flow.

    stage_fn: (activation, chunk_index) -> activation, chunk_index in
      {0, 1} selecting the device-local layer chunk.
    Returns [M, microbatch, ...] outputs, valid on the LAST device
    (which hosts the final virtual stage 2pp-1); zeros elsewhere.
    """
    pp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    perm = [(j, (j + 1) % pp) for j in range(pp)]
    nblocks = -(-m // pp)
    ticks = 2 * pp * nblocks + pp - 1

    def tick(carry, t):
        recv, outputs = carry
        tj = t - idx                      # ticks since this activation
        lap = jnp.where(tj >= 0, (tj // pp) % 2, 0)
        inj = tj - lap * pp               # its injection tick at dev 0
        mb_idx = inj - (inj // (2 * pp)) * pp
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(mb_idx, 0, m - 1), keepdims=False)
        inject = jnp.logical_and(idx == 0, lap == 0)
        out = stage_fn(jnp.where(inject, mb, recv), lap)
        w = jnp.clip(mb_idx, 0, m - 1)
        valid = ((idx == pp - 1) & (lap == 1) & (tj >= 0)
                 & (mb_idx >= 0) & (mb_idx < m))
        cur = lax.dynamic_index_in_dim(outputs, w, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, out, cur), w, axis=0)
        recv = lax.ppermute(out, axis_name, perm)
        return (recv, outputs), None

    carry0 = (jnp.zeros_like(x_microbatches[0]),
              jnp.zeros_like(x_microbatches))
    (_, outputs), _ = lax.scan(tick, carry0, jnp.arange(ticks))
    return outputs


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def last_stage_broadcast(x, axis_name: str):
    """Broadcast the last stage's value to every stage (mask + psum).

    custom_vjp: the cotangent returns to the last stage alone, at unit
    scale.  A raw psum's transpose is psum under shard_map AD, which
    would hand the pipeline ``pp×`` the true cotangent (one copy per
    stage's identical loss replica)."""
    return _mask_psum(x, axis_name)


def _mask_psum(x, axis_name):
    pp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    return lax.psum(jnp.where(idx == pp - 1, x, jnp.zeros_like(x)),
                    axis_name)


def _lsb_fwd(x, axis_name):
    return _mask_psum(x, axis_name), None


def _lsb_bwd(axis_name, _, g):
    pp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    return (jnp.where(idx == pp - 1, g, jnp.zeros_like(g)),)


last_stage_broadcast.defvjp(_lsb_fwd, _lsb_bwd)
