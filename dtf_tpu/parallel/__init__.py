"""Parallelism library: collectives, sequence parallelism, tensor
parallelism, and the async parameter-server mode.

The reference reaches all of this through third-party native backends —
NCCL ring allreduce, TF's grpc distributed runtime, collective-allreduce
kernels (SURVEY.md §5.8).  Here the synchronous paths are XLA
collectives over ICI/DCN emitted from `shard_map`/`pjit`, and the async
parameter-server path is a first-party C++ parameter store
(`parallel.ps`).
"""

from dtf_tpu.parallel.collectives import (all_gather, all_reduce_mean,
                                          all_reduce_sum, axis_index,
                                          axis_size, broadcast_from,
                                          reduce_scatter, ring_shift)
from dtf_tpu.parallel.ring_attention import ring_attention

__all__ = [
    "all_gather",
    "all_reduce_mean",
    "all_reduce_sum",
    "axis_index",
    "axis_size",
    "broadcast_from",
    "reduce_scatter",
    "ring_shift",
    "ring_attention",
]
