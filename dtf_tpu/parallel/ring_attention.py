"""Ring attention — sequence/context parallelism over the 'seq' mesh axis.

Long-context capability (absent from the vision-only reference, SURVEY.md
§5.7, but first-class here): the sequence dimension is sharded across
devices, so context length scales linearly with the ring size instead of
being capped by one device's HBM.

Mechanics (Liu et al., Ring Attention with Blockwise Transformers): each
device owns one query shard and one K/V shard.  The K/V shards rotate
around the ring — `lax.ppermute` to the clockwise neighbor, which XLA
schedules over ICI *overlapped with the attention compute of the current
block*.  Each device folds every visiting K/V block into the
online-softmax carry (`ops.blockwise.block_accumulate` — the same math
as flash attention, with "block" = "shard").  After `ring_size` steps
every query has attended to the full global sequence; no [S, S] score
matrix and no all-gather of K/V ever materializes.

Causal masking uses absolute positions derived from `axis_index`, so a
rotating shard is masked by where it *came from*, not where it is.

`ring_attention` is written to run inside `shard_map` (it is just a
collective-using function); `ring_self_attention` wraps it over a mesh
for direct use.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.ops import blockwise as bw
from dtf_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def ring_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                   causal: bool = False, scale: Optional[float] = None):
    """Attention over a sequence-sharded q/k/v.  Call inside shard_map.

    q, k, v: [batch, seq_shard, heads, head_dim] — the local shard of a
    globally [batch, seq, heads, head_dim] array sharded on ``axis_name``.
    Returns the local output shard, same shape as q.
    """
    orig_dtype = q.dtype
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    s_loc = q.shape[-3]

    to_bhsd = lambda x: jnp.swapaxes(x, -3, -2).astype(jnp.float32)
    qt = to_bhsd(q)
    q_pos = idx * s_loc + jnp.arange(s_loc)

    o0 = jnp.zeros_like(qt)
    m0 = jnp.full(qt.shape[:-1], bw.NEG_INF, jnp.float32)
    l0 = jnp.zeros(qt.shape[:-1], jnp.float32)

    def body(carry, t):
        o, m, l, kc, vc = carry
        bias = None
        if causal:
            src = (idx - t) % n            # which global shard kc holds now
            k_pos = src * s_loc + jnp.arange(s_loc)
            bias = bw.causal_bias(q_pos, k_pos)
        o, m, l = bw.block_accumulate(o, m, l, qt, to_bhsd(kc), to_bhsd(vc),
                                      scale, bias)
        # rotate K/V to the next device; ICI neighbor exchange that XLA
        # overlaps with the next block's compute
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o, m, l, kc, vc), None

    (o, m, l, _, _), _ = lax.scan(body, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    out = bw.finalize(o, l)
    return jnp.swapaxes(out, -3, -2).astype(orig_dtype)


def ring_self_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                        scale: Optional[float] = None,
                        data_axis: str = DATA_AXIS,
                        seq_axis: str = SEQ_AXIS,
                        model_axis: Optional[str] = MODEL_AXIS):
    """Ring attention over globally-shaped [B, S, H, D] arrays.

    Batch shards over ``data_axis``, sequence over ``seq_axis``, heads
    over ``model_axis`` (tensor parallelism composes freely with the
    ring — heads never communicate).  Usable under an outer `jit`; the
    inner shard_map is differentiable.
    """
    spec = P(data_axis, seq_axis, model_axis, None)
    fn = jax.shard_map(
        partial(ring_attention, axis_name=seq_axis, causal=causal,
                scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
