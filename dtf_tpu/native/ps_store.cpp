// Async parameter-store server — the native core of the opt-in
// asynchronous parameter_server mode.
//
// The reference's PS path delegates this to the TensorFlow C++ grpc
// distributed runtime: a PS rank hosts the variables and serves
// push/pull forever while workers step asynchronously (reference
// ps_server/resnet_imagenet_main_dist_ps_0.py:38-50, log evidence
// "Started server with target: grpc://localhost:1111", SURVEY §3.4).
// This is the TPU-native framework's equivalent: a small threaded TCP
// server holding the flat parameter vector plus Keras-SGD momentum
// slots (velocity lives on the PS, like TF optimizer slot variables),
// applying pushed gradients under a mutex — i.e. HogWild-style async
// SGD with atomic-per-push updates, the same consistency model the
// reference's PS gives per-variable.
//
// Wire protocol (little-endian, length-free framing by fixed headers):
//   request  = u8 opcode, then opcode-specific payload
//   INIT=1   : u64 n, f32[n] params        -> u8 st, u64 n, u64 version
//              (first INIT wins; st=1 when already initialized)
//   PULL=2   :                              -> u8 st, u64 n, u64 version, f32[n]
//              (st=2 when not yet initialized; no payload then)
//   PUSH=3   : f32 lr, u64 n, f32[n] grads -> u8 st, u64 version
//              (v = momentum*v - lr*g; p += v  — Keras SGD form)
//   INFO=4   :                              -> u8 st, u64 n, u64 version
//   DONE=5   :                              -> u8 st   (worker finished)
//   SHUTDOWN=6:                             -> u8 st   (server exits)
//   PULL16=7 :                              -> u8 st, u64 n, u64 version, bf16[n]
//   PUSH16=8 : f32 lr, u64 n, bf16[n] grads-> u8 st, u64 version
//
// The bf16 ops (--ps_wire bf16) halve wire traffic: params/grads cross
// the network as round-to-nearest-even bfloat16 while the store's
// master params and momentum stay f32 (wire compression only — the
// update math is unchanged).  For ResNet-50 that is ~100 MB/step/worker
// instead of ~200 MB.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t {
  OP_INIT = 1,
  OP_PULL = 2,
  OP_PUSH = 3,
  OP_INFO = 4,
  OP_DONE = 5,
  OP_SHUTDOWN = 6,
  OP_PULL16 = 7,
  OP_PUSH16 = 8,
};

// f32 -> bf16 with round-to-nearest-even (the numpy/JAX convention).
// NaNs are preserved explicitly (truncate + quiet bit): the RNE add
// would carry a low-mantissa NaN payload into Inf, or wrap to zero.
inline uint16_t f32_to_bf16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  if ((u & 0x7F800000u) == 0x7F800000u && (u & 0x007FFFFFu))
    return static_cast<uint16_t>((u >> 16) | 0x0040u);
  const uint32_t rounded = u + 0x7FFFu + ((u >> 16) & 1u);
  return static_cast<uint16_t>(rounded >> 16);
}

inline float bf16_to_f32(uint16_t h) {
  const uint32_t u = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &u, 4);
  return f;
}

// Parameters larger than this are a corrupt/hostile request, not a real
// model (4B f32 = 16 GiB).
constexpr uint64_t kMaxParams = 1ull << 32;

// Snapshot file format (little-endian), shared byte-for-byte with the
// Python fallback store so either build restores the other's dump:
//   8-byte magic "DTFPSNP1", u64 version, u64 n,
//   f32 params[n], f32 velocity[n],
//   then an OPTIONAL footer: 8-byte magic "DTFPSDN1", u64 done_count.
// The footer persists the DONE tally so a PS restarted after a worker
// finished and exited cannot hang wait(num_workers) one short; restore
// accepts footer-less (pre-footer) snapshots with done_count = 0.
constexpr char kSnapMagic[8] = {'D', 'T', 'F', 'P', 'S', 'N', 'P', '1'};
constexpr char kSnapFooterMagic[8] = {'D', 'T', 'F', 'P', 'S', 'D', 'N',
                                      '1'};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t got = recv(fd, p, n, 0);
    if (got < 0 && errno == EINTR) continue;  // CPython installs signal
    if (got <= 0) return false;               // handlers without SA_RESTART
    p += got;
    n -= static_cast<size_t>(got);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t put = send(fd, p, n, MSG_NOSIGNAL);
    if (put < 0 && errno == EINTR) continue;
    if (put <= 0) return false;
    p += put;
    n -= static_cast<size_t>(put);
  }
  return true;
}

struct PsServer {
  int listen_fd = -1;
  int port = 0;
  float momentum = 0.9f;

  std::mutex mu;                 // guards params/velocity/version
  std::vector<float> params;
  std::vector<float> velocity;
  uint64_t version = 0;
  bool initialized = false;

  std::mutex state_mu;           // guards done_count/stopping + cv
  std::condition_variable cv;
  int done_count = 0;
  bool stopping = false;

  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;     // shut down on stop so joins can't hang
  std::mutex threads_mu;

  void handle_conn(int fd);
  void accept_loop();
};

void PsServer::handle_conn(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::vector<float> scratch;
  std::vector<uint16_t> scratch16;
  for (;;) {
    uint8_t op;
    if (!read_full(fd, &op, 1)) break;
    if (op == OP_INIT) {
      uint64_t n;
      if (!read_full(fd, &n, 8) || n == 0 || n > kMaxParams) break;
      // a hostile/corrupt n below the cap must drop this connection,
      // not std::terminate the store hosting every worker's state
      try {
        scratch.resize(n);
      } catch (const std::bad_alloc&) {
        break;
      }
      if (!read_full(fd, scratch.data(), n * 4)) break;
      uint8_t st = 0;
      uint64_t ver, outn;
      bool alloc_failed = false;
      {
        std::lock_guard<std::mutex> lk(mu);
        if (!initialized) {
          try {
            params = scratch;
            velocity.assign(n, 0.0f);
            initialized = true;
          } catch (const std::bad_alloc&) {
            params.clear();
            velocity.clear();
            alloc_failed = true;
          }
        } else {
          st = 1;
        }
        ver = version;
        outn = params.size();
      }
      if (alloc_failed) break;
      uint8_t resp[17];
      resp[0] = st;
      memcpy(resp + 1, &outn, 8);
      memcpy(resp + 9, &ver, 8);
      if (!write_full(fd, resp, 17)) break;
    } else if (op == OP_PULL) {
      std::unique_lock<std::mutex> lk(mu);
      if (!initialized) {
        lk.unlock();
        uint8_t st = 2;
        if (!write_full(fd, &st, 1)) break;
        continue;
      }
      // snapshot under the lock, send outside it
      scratch = params;
      uint64_t ver = version, n = scratch.size();
      lk.unlock();
      uint8_t hdr[17];
      hdr[0] = 0;
      memcpy(hdr + 1, &n, 8);
      memcpy(hdr + 9, &ver, 8);
      if (!write_full(fd, hdr, 17)) break;
      if (!write_full(fd, scratch.data(), n * 4)) break;
    } else if (op == OP_PUSH) {
      float lr;
      uint64_t n;
      if (!read_full(fd, &lr, 4) || !read_full(fd, &n, 8) ||
          n == 0 || n > kMaxParams)
        break;
      try {
        scratch.resize(n);
      } catch (const std::bad_alloc&) {
        break;
      }
      if (!read_full(fd, scratch.data(), n * 4)) break;
      uint8_t st = 0;
      uint64_t ver = 0;
      {
        std::lock_guard<std::mutex> lk(mu);
        if (!initialized || params.size() != n) {
          st = 2;
        } else {
          float* p = params.data();
          float* v = velocity.data();
          const float* g = scratch.data();
          const float m = momentum;
          for (uint64_t i = 0; i < n; ++i) {
            v[i] = m * v[i] - lr * g[i];
            p[i] += v[i];
          }
          ver = ++version;
        }
      }
      uint8_t resp[9];
      resp[0] = st;
      memcpy(resp + 1, &ver, 8);
      if (!write_full(fd, resp, 9)) break;
    } else if (op == OP_PULL16) {
      std::unique_lock<std::mutex> lk(mu);
      if (!initialized) {
        lk.unlock();
        uint8_t st = 2;
        if (!write_full(fd, &st, 1)) break;
        continue;
      }
      uint64_t ver = version, n = params.size();
      // snapshot under the lock (plain vector copy, same cost as the
      // f32 OP_PULL); the element-wise bf16 conversion runs unlocked so
      // concurrent pushes don't serialize behind it
      try {
        scratch = params;
      } catch (const std::bad_alloc&) {
        break;
      }
      lk.unlock();
      try {
        scratch16.resize(n);
      } catch (const std::bad_alloc&) {
        break;
      }
      for (uint64_t i = 0; i < n; ++i)
        scratch16[i] = f32_to_bf16(scratch[i]);
      uint8_t hdr[17];
      hdr[0] = 0;
      memcpy(hdr + 1, &n, 8);
      memcpy(hdr + 9, &ver, 8);
      if (!write_full(fd, hdr, 17)) break;
      if (!write_full(fd, scratch16.data(), n * 2)) break;
    } else if (op == OP_PUSH16) {
      float lr;
      uint64_t n;
      if (!read_full(fd, &lr, 4) || !read_full(fd, &n, 8) ||
          n == 0 || n > kMaxParams)
        break;
      try {
        scratch16.resize(n);
      } catch (const std::bad_alloc&) {
        break;
      }
      if (!read_full(fd, scratch16.data(), n * 2)) break;
      uint8_t st = 0;
      uint64_t ver = 0;
      {
        std::lock_guard<std::mutex> lk(mu);
        if (!initialized || params.size() != n) {
          st = 2;
        } else {
          float* p = params.data();
          float* v = velocity.data();
          const float m = momentum;
          for (uint64_t i = 0; i < n; ++i) {
            v[i] = m * v[i] - lr * bf16_to_f32(scratch16[i]);
            p[i] += v[i];
          }
          ver = ++version;
        }
      }
      uint8_t resp[9];
      resp[0] = st;
      memcpy(resp + 1, &ver, 8);
      if (!write_full(fd, resp, 9)) break;
    } else if (op == OP_INFO) {
      uint8_t resp[17];
      std::lock_guard<std::mutex> lk(mu);
      uint64_t n = params.size(), ver = version;
      resp[0] = initialized ? 0 : 2;
      memcpy(resp + 1, &n, 8);
      memcpy(resp + 9, &ver, 8);
      if (!write_full(fd, resp, 17)) break;
    } else if (op == OP_DONE) {
      // ack BEFORE notifying: wait() returning triggers stop(), which
      // tears down this connection — the ack must already be in flight
      uint8_t st = 0;
      bool ok = write_full(fd, &st, 1);
      {
        std::lock_guard<std::mutex> lk(state_mu);
        ++done_count;
      }
      cv.notify_all();
      if (!ok) break;
    } else if (op == OP_SHUTDOWN) {
      {
        std::lock_guard<std::mutex> lk(state_mu);
        stopping = true;
      }
      cv.notify_all();
      uint8_t st = 0;
      write_full(fd, &st, 1);
      // unblocking accept() is dtf_ps_stop's job — touching listen_fd
      // from this thread races with stop() having already close()d it
      // (fd-number reuse)
      break;
    } else {
      break;  // unknown opcode: drop the connection
    }
  }
  // remove from the tracked set under the lock before closing, so stop()
  // can never shutdown() an fd number the OS has already reused
  {
    std::lock_guard<std::mutex> lk(threads_mu);
    for (auto& tracked : conn_fds)
      if (tracked == fd) tracked = -1;
  }
  close(fd);
}

void PsServer::accept_loop() {
  for (;;) {
    int fd = accept(listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;  // same SA_RESTART exposure as recv
      std::lock_guard<std::mutex> lk(state_mu);
      if (stopping) return;
      return;  // listen socket closed/broken
    }
    std::lock_guard<std::mutex> lk(threads_mu);
    conn_fds.push_back(fd);
    conn_threads.emplace_back(&PsServer::handle_conn, this, fd);
  }
}

}  // namespace

extern "C" {

// Client-side wire conversion (VERDICT r3 #6): the worker's numpy RNE
// f32→bf16 (several full-array temporaries under the GIL) cost more
// than the loopback wire saved, so the only committed bf16 measurement
// showed the feature losing.  One C pass per direction — same
// f32_to_bf16/bf16_to_f32 the store itself uses, GIL released via
// ctypes — makes the halved wire a net win even on loopback.
void dtf_f32_to_bf16(const float* in, uint16_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = f32_to_bf16(in[i]);
}

void dtf_bf16_to_f32(const uint16_t* in, float* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = bf16_to_f32(in[i]);
}

// Binds + listens on 0.0.0.0:port (port 0 = ephemeral) WITHOUT serving
// yet: connections queue in the listen backlog until
// dtf_ps_begin_accept.  The gap is where a restart restores its
// snapshot — no worker INIT can race the restore.  Returns an opaque
// handle or nullptr on bind failure.
void* dtf_ps_start_paused(int port, float momentum) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, 64) < 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  auto* s = new PsServer;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->momentum = momentum;
  return s;
}

// Starts the accept loop (idempotent is NOT needed: call exactly once).
void dtf_ps_begin_accept(void* handle) {
  auto* s = static_cast<PsServer*>(handle);
  s->accept_thread = std::thread(&PsServer::accept_loop, s);
}

// Starts a server and serves immediately (bind + accept).
void* dtf_ps_start(int port, float momentum) {
  void* s = dtf_ps_start_paused(port, momentum);
  if (s) dtf_ps_begin_accept(s);
  return s;
}

int dtf_ps_port(void* handle) {
  return static_cast<PsServer*>(handle)->port;
}

// Blocks until `n_done` workers reported DONE or SHUTDOWN arrived.
void dtf_ps_wait(void* handle, int n_done) {
  auto* s = static_cast<PsServer*>(handle);
  std::unique_lock<std::mutex> lk(s->state_mu);
  s->cv.wait(lk, [&] { return s->stopping || s->done_count >= n_done; });
}

// Atomic snapshot of params+velocity+version: copy under the lock,
// write to <path>.tmp, fsync, rename.  A crash mid-write never damages
// the previous snapshot.  Returns 0 on success, -1 (not initialized),
// -2 (I/O failure).
int dtf_ps_snapshot(void* handle, const char* path) {
  auto* s = static_cast<PsServer*>(handle);
  // done_count is read BEFORE the params copy: a DONE is only sent
  // after the worker's last push was acked, so any DONE counted here is
  // already reflected in the params copied below — the reverse order
  // could persist a "done" worker whose final pushes are missing
  uint64_t done_count;
  {
    std::lock_guard<std::mutex> lk(s->state_mu);
    done_count = static_cast<uint64_t>(s->done_count);
  }
  std::vector<float> params, velocity;
  uint64_t version;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    if (!s->initialized) return -1;
    params = s->params;
    velocity = s->velocity;
    version = s->version;
  }
  const std::string tmp = std::string(path) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -2;
  const uint64_t n = params.size();
  bool ok = fwrite(kSnapMagic, 1, 8, f) == 8 &&
            fwrite(&version, 8, 1, f) == 1 && fwrite(&n, 8, 1, f) == 1 &&
            fwrite(params.data(), 4, n, f) == n &&
            fwrite(velocity.data(), 4, n, f) == n &&
            fwrite(kSnapFooterMagic, 1, 8, f) == 8 &&
            fwrite(&done_count, 8, 1, f) == 1;
  if (ok) ok = fflush(f) == 0 && fsync(fileno(f)) == 0;
  ok = (fclose(f) == 0) && ok;
  if (!ok || rename(tmp.c_str(), path) != 0) {
    remove(tmp.c_str());
    return -2;
  }
  return 0;
}

// Loads a snapshot into the store (marks it initialized, so worker
// INITs after a restore get st=1 and pull the restored state instead
// of re-proposing).  Returns 0 on success, -1 (open failure), -2
// (corrupt/truncated file).
int dtf_ps_restore(void* handle, const char* path) {
  auto* s = static_cast<PsServer*>(handle);
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  char magic[8];
  uint64_t version, n;
  bool ok = fread(magic, 1, 8, f) == 8 &&
            memcmp(magic, kSnapMagic, 8) == 0 &&
            fread(&version, 8, 1, f) == 1 && fread(&n, 8, 1, f) == 1 &&
            n > 0 && n <= kMaxParams;
  std::vector<float> params, velocity;
  if (ok) {
    try {
      params.resize(n);
      velocity.resize(n);
    } catch (const std::bad_alloc&) {
      ok = false;
    }
  }
  if (ok)
    ok = fread(params.data(), 4, n, f) == n &&
         fread(velocity.data(), 4, n, f) == n;
  uint64_t done_count = 0;  // footer-less (pre-footer) snapshots: 0
  if (ok) {
    char footer_magic[8];
    const size_t got = fread(footer_magic, 1, 8, f);
    if (got == 8) {
      ok = memcmp(footer_magic, kSnapFooterMagic, 8) == 0 &&
           fread(&done_count, 8, 1, f) == 1 && fgetc(f) == EOF;
    } else {
      ok = got == 0 && feof(f);  // no footer: clean EOF required
    }
  }
  fclose(f);
  if (!ok) return -2;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->params = std::move(params);
    s->velocity = std::move(velocity);
    s->version = version;
    s->initialized = true;
  }
  {
    std::lock_guard<std::mutex> lk(s->state_mu);
    s->done_count = static_cast<int>(done_count);
  }
  s->cv.notify_all();
  return 0;
}

// Stops accepting, joins all threads, frees the handle.
void dtf_ps_stop(void* handle) {
  auto* s = static_cast<PsServer*>(handle);
  {
    std::lock_guard<std::mutex> lk(s->state_mu);
    s->stopping = true;
  }
  s->cv.notify_all();
  shutdown(s->listen_fd, SHUT_RDWR);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // close only after the accept loop has exited: releasing the fd number
  // while accept() may still run invites fd-reuse races
  close(s->listen_fd);
  std::vector<std::thread> threads;
  {
    std::lock_guard<std::mutex> lk(s->threads_mu);
    for (int fd : s->conn_fds)
      if (fd >= 0) shutdown(fd, SHUT_RDWR);
    threads.swap(s->conn_threads);
  }
  // join outside the lock: an exiting conn thread needs threads_mu to
  // untrack its fd
  for (auto& t : threads)
    if (t.joinable()) t.join();
  delete s;
}

}  // extern "C"
