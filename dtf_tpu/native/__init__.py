"""ctypes bindings for the C++ data runtime (libdtf_native.so).

Build with `make -C dtf_tpu/native`.  Every consumer degrades to the
pure-Python implementation when the library is absent, so the build is
an optimization, not a requirement.  ctypes foreign calls release the
GIL, so Python worker threads get true decode parallelism.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "libdtf_native.so")
_lib: Optional[ctypes.CDLL] = None


def load() -> Optional[ctypes.CDLL]:
    """Returns the loaded library, or None when not built."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    # Input buffers are declared c_char_p so Python `bytes` pass
    # zero-copy (the C side is const and never writes).
    lib.dtf_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.dtf_crc32c.restype = ctypes.c_uint32

    lib.dtf_tfr_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.dtf_tfr_open.restype = ctypes.c_void_p
    lib.dtf_tfr_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(u8p)]
    lib.dtf_tfr_next.restype = ctypes.c_int64
    lib.dtf_tfr_close.argtypes = [ctypes.c_void_p]

    lib.dtf_jpeg_shape.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int),
                                   ctypes.POINTER(ctypes.c_int)]
    lib.dtf_jpeg_shape.restype = ctypes.c_int
    lib.dtf_jpeg_decode_crop.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, u8p]
    lib.dtf_jpeg_decode_crop.restype = ctypes.c_int
    lib.dtf_jpeg_decode_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_int, u8p, ctypes.c_int]
    lib.dtf_jpeg_decode_batch.restype = ctypes.c_int
    f32p = ctypes.POINTER(ctypes.c_float)
    # Libraries exporting dtf_wire_u8 take a void* output plus a
    # trailing out_u8 selector on the fused batch ops (the uint8
    # host→device wire); older builds keep the f32-only signatures.
    u8_wire = hasattr(lib, "dtf_wire_u8")
    outp = ctypes.c_void_p if u8_wire else f32p
    tail = [ctypes.c_int] if u8_wire else []
    lib.dtf_jpeg_decode_crop_resize_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int), u8p, ctypes.c_int,
        ctypes.c_int, f32p, outp, u8p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int] + tail
    lib.dtf_jpeg_decode_crop_resize_batch.restype = ctypes.c_int
    lib.dtf_jpeg_eval_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, f32p,
        outp, u8p, ctypes.c_int, ctypes.c_int] + tail
    lib.dtf_jpeg_eval_batch.restype = ctypes.c_int
    if hasattr(lib, "dtf_train_example_batch"):
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.dtf_train_example_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int, f32p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, outp, i32p, i32p,
            u8p, u8p] + tail
        lib.dtf_train_example_batch.restype = ctypes.c_int
    if hasattr(lib, "dtf_f32_to_bf16"):
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.dtf_f32_to_bf16.argtypes = [f32p, u16p, ctypes.c_int64]
        lib.dtf_f32_to_bf16.restype = None
        lib.dtf_bf16_to_f32.argtypes = [u16p, f32p, ctypes.c_int64]
        lib.dtf_bf16_to_f32.restype = None
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def crc32c(data: bytes) -> int:
    lib = load()
    assert lib is not None
    return lib.dtf_crc32c(data, len(data))


def read_tfrecord_file(path: str, verify_crc: bool = False):
    """Native streaming TFRecord reader; same contract as
    records.read_tfrecord_file."""
    lib = load()
    assert lib is not None
    handle = lib.dtf_tfr_open(path.encode(), int(verify_crc))
    if not handle:
        raise IOError(f"{path}: cannot open")
    try:
        data_p = ctypes.POINTER(ctypes.c_uint8)()
        while True:
            n = lib.dtf_tfr_next(handle, ctypes.byref(data_p))
            if n == -1:
                return
            if n < 0:
                raise IOError(f"{path}: corrupt or truncated record")
            yield ctypes.string_at(data_p, n)
    finally:
        lib.dtf_tfr_close(handle)
