// dtf_native — the framework's C++ data runtime.
//
// TPU-native equivalent of the reference's load-bearing tf.data C++
// kernels (SURVEY.md §2.4): TFRecordDataset record framing + crc32c,
// JPEG decode (libjpeg) incl. fused decode-and-crop via scanline
// windowing (the tf.image.decode_and_crop_jpeg equivalent,
// imagenet_preprocessing.py:363-368), and a multithreaded batch
// decoder that runs outside the Python GIL.
//
// Exposed as a plain C ABI consumed with ctypes (no pybind11 in this
// environment).  Build: `make -C dtf_tpu/native`.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <mutex>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string_view>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>

extern "C" {

// ---------------------------------------------------------------------------
// crc32c (Castagnoli) — slicing-by-8
// ---------------------------------------------------------------------------

static uint32_t crc_table[8][256];

// Built once under std::call_once: callers arrive from Python threads
// with the GIL released, so first use may be concurrent.
static std::once_flag crc_once;

static void crc_build_tables() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c >> 1) ^ (0x82F63B78u * (c & 1));
    crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = crc_table[0][i];
    for (int s = 1; s < 8; s++) {
      c = (c >> 8) ^ crc_table[0][c & 0xFF];
      crc_table[s][i] = c;
    }
  }
}

static void crc_init() { std::call_once(crc_once, crc_build_tables); }

uint32_t dtf_crc32c(const uint8_t* data, int64_t n) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  while (n >= 8) {
    uint64_t word;
    memcpy(&word, data, 8);
    word ^= c;
    c = crc_table[7][word & 0xFF] ^ crc_table[6][(word >> 8) & 0xFF] ^
        crc_table[5][(word >> 16) & 0xFF] ^ crc_table[4][(word >> 24) & 0xFF] ^
        crc_table[3][(word >> 32) & 0xFF] ^ crc_table[2][(word >> 40) & 0xFF] ^
        crc_table[1][(word >> 48) & 0xFF] ^ crc_table[0][(word >> 56) & 0xFF];
    data += 8;
    n -= 8;
  }
  while (n-- > 0) c = (c >> 8) ^ crc_table[0][(c ^ *data++) & 0xFF];
  return c ^ 0xFFFFFFFFu;
}

static uint32_t masked_crc(const uint8_t* p, int64_t n) {
  uint32_t crc = dtf_crc32c(p, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// TFRecord streaming reader
// ---------------------------------------------------------------------------

struct TfrReader {
  FILE* f;
  int verify;
  std::vector<uint8_t> buf;
};

void* dtf_tfr_open(const char* path, int verify_crc) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new TfrReader{f, verify_crc, {}};
  return r;
}

// Returns record length (>=0) with *data pointing at an internal buffer
// valid until the next call; -1 on clean EOF; -2 on corruption/truncation.
int64_t dtf_tfr_next(void* handle, const uint8_t** data) {
  auto* r = static_cast<TfrReader*>(handle);
  uint8_t header[12];
  size_t got = fread(header, 1, 12, r->f);
  if (got == 0) return -1;
  if (got < 12) return -2;
  uint64_t len;
  memcpy(&len, header, 8);
  if (r->verify) {
    uint32_t crc;
    memcpy(&crc, header + 8, 4);
    if (masked_crc(header, 8) != crc) return -2;
  }
  // The length field is untrusted file content: a corrupt header must
  // surface as a catchable read error, not a std::bad_alloc (or a
  // len+4 wraparound) escaping through the C ABI.
  if (len > (1ull << 33)) return -2;  // 8 GiB: far beyond any real record
  try {
    r->buf.resize(len + 4);
  } catch (const std::bad_alloc&) {
    return -2;  // corrupt length below the cap but beyond available memory
  }
  if (fread(r->buf.data(), 1, len + 4, r->f) != len + 4) return -2;
  if (r->verify) {
    uint32_t crc;
    memcpy(&crc, r->buf.data() + len, 4);
    if (masked_crc(r->buf.data(), len) != crc) return -2;
  }
  *data = r->buf.data();
  return static_cast<int64_t>(len);
}

void dtf_tfr_close(void* handle) {
  auto* r = static_cast<TfrReader*>(handle);
  fclose(r->f);
  delete r;
}

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg), with optional crop window
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jmp;
};

static void jpeg_err_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jmp, 1);
}

// Reads the header only: fills h/w. Returns 0 on success.
int dtf_jpeg_shape(const uint8_t* buf, int64_t len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  *h = cinfo.image_height;
  *w = cinfo.image_width;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decodes RGB into out (size ch*cw*3), reading only rows [y, y+ch) and
// columns [x, x+cw) — the fused decode-and-crop. Pass y=x=0 and
// ch=cw=full size for a plain decode. fast_dct selects JDCT_IFAST
// (~1.3-2x faster IDCT, ±1-2 LSB vs JDCT_ISLOW — fine for train-time
// augmentation, off for anything parity-sensitive). scale_num (1..7)
// selects libjpeg's DCT-space scale_num/8 scaled decode (8 = none);
// the crop window (y, x, ch, cw) is then in SCALED coordinates.
// Returns 0 on success.
static int jpeg_decode_crop_impl(const uint8_t* buf, int64_t len, int y,
                                 int x, int ch, int cw, uint8_t* out,
                                 int fast_dct, int scale_num = 8) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  if (fast_dct) cinfo.dct_method = JDCT_IFAST;
  if (scale_num < 8) {
    cinfo.scale_num = scale_num;
    cinfo.scale_denom = 8;
  }
  jpeg_start_decompress(&cinfo);
  const int W = cinfo.output_width, H = cinfo.output_height;
  if (y < 0 || x < 0 || y + ch > H || x + cw > W) {
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  std::vector<uint8_t> row(static_cast<size_t>(W) * 3);
  uint8_t* rowp = row.data();
  if (y > 0) jpeg_skip_scanlines(&cinfo, y);
  for (int r = 0; r < ch; r++) {
    jpeg_read_scanlines(&cinfo, &rowp, 1);
    memcpy(out + static_cast<size_t>(r) * cw * 3, rowp + x * 3,
           static_cast<size_t>(cw) * 3);
  }
  jpeg_abort_decompress(&cinfo);  // skip remaining rows cheaply
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int dtf_jpeg_decode_crop(const uint8_t* buf, int64_t len, int y, int x,
                         int ch, int cw, uint8_t* out) {
  return jpeg_decode_crop_impl(buf, len, y, x, ch, cw, out, 0);
}

// ---------------------------------------------------------------------------
// Multithreaded batch decode-crop: n images decoded in parallel into a
// caller-provided contiguous buffer [n, ch, cw, 3] (GIL-free on the
// Python side).  crops is n×4 ints (y, x, ch_i==ch, cw_i==cw for now).
// Returns number of failures.
// ---------------------------------------------------------------------------

int dtf_jpeg_decode_batch(const uint8_t** bufs, const int64_t* lens, int n,
                          const int* crops, int ch, int cw, uint8_t* out,
                          int num_threads) {
  std::atomic<int> next(0), failures(0);
  auto work = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      const int* c = crops + i * 4;
      if (c[2] != ch || c[3] != cw) {  // fixed output layout required
        failures.fetch_add(1);
        continue;
      }
      if (dtf_jpeg_decode_crop(bufs[i], lens[i], c[0], c[1], c[2], c[3],
                               out + static_cast<size_t>(i) * ch * cw * 3)) {
        failures.fetch_add(1);
      }
    }
  };
  if (num_threads <= 1) {
    work();
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < num_threads; t++) threads.emplace_back(work);
    for (auto& t : threads) t.join();
  }
  return failures.load();
}

}  // extern "C" — the templated sampler below needs C++ linkage

// ---------------------------------------------------------------------------
// Fused decode→crop→(flip)→bilinear-resize→store batch — the whole
// ImageNet train-time augmentation (imagenet_preprocessing.py
// _decode_crop_and_flip + _resize_image + _mean_image_subtraction) per
// image in one C++ pass, n images across num_threads threads, GIL-free.
// Bilinear = half-pixel centers, no antialias (tf.image.resize v2).
// Per-image variable crop windows; fixed [oh, ow] output in one of two
// wire formats (the Store policies below).
// statuses[i] = 0 ok / 1 failed (caller re-decodes failures its own
// way).  Returns the failure count.
// ---------------------------------------------------------------------------

// Output stores for the bilinear sampler.  StoreF32Sub: float32 with
// per-channel mean subtraction — the host-normalized wire.  StoreU8:
// round-half-up to uint8 (floorf(v + 0.5f), matching the Python
// fallback's np.floor(v + 0.5)) with NO normalization — the TPU-native
// wire: batches ship 4x fewer bytes host→device and the mean-subtract /
// standardize runs as the first op inside the compiled step.  Bilinear
// output of uint8 inputs is a convex combination in [0, 255]; the clamp
// only guards fp drift.
struct StoreF32Sub {
  float* dst;
  const float* sub;
  inline void put(size_t idx, int ch, float v) const {
    dst[idx] = v - sub[ch];
  }
};

struct StoreU8 {
  uint8_t* dst;
  inline void put(size_t idx, int ch, float v) const {
    (void)ch;
    float r = floorf(v + 0.5f);
    dst[idx] = static_cast<uint8_t>(r < 0.f ? 0.f : (r > 255.f ? 255.f : r));
  }
};

// Generic bilinear sampler: output pixel (r, c) reads source position
// (y_off + r*y_step, x_off + c*x_step), clamped — tf.image.resize v2
// semantics when y_off = 0.5*y_step - 0.5 (plain resize), and the
// aspect-preserving-resize + central-crop composition when the offsets
// carry the crop origin.
template <typename Store>
static void bilinear_sample_store(const uint8_t* src, int sh, int sw,
                                  int oh, int ow, int flip,
                                  float y_off, float y_step, float x_off,
                                  float x_step, const Store& st) {
  // column sampling tables, computed once (not per row)
  std::vector<int> xas(ow), xbs(ow);
  std::vector<float> wxs(ow);
  for (int c = 0; c < ow; c++) {
    // flip(resize(x)) == resize(flip(x)) for symmetric half-pixel
    // sampling, so the flip fuses into the source column lookup
    int cc = flip ? (ow - 1 - c) : c;
    float fx = x_off + cc * x_step;
    int x0 = static_cast<int>(floorf(fx));
    wxs[c] = fx - x0;
    xas[c] = 3 * (x0 < 0 ? 0 : (x0 >= sw ? sw - 1 : x0));
    xbs[c] = 3 * (x0 + 1 < 0 ? 0 : (x0 + 1 >= sw ? sw - 1 : x0 + 1));
  }
  for (int r = 0; r < oh; r++) {
    float fy = y_off + r * y_step;
    int y0 = static_cast<int>(floorf(fy));
    float wy = fy - y0;
    int ya = y0 < 0 ? 0 : (y0 >= sh ? sh - 1 : y0);
    int yb = y0 + 1 < 0 ? 0 : (y0 + 1 >= sh ? sh - 1 : y0 + 1);
    const uint8_t* rowa = src + static_cast<size_t>(ya) * sw * 3;
    const uint8_t* rowb = src + static_cast<size_t>(yb) * sw * 3;
    const size_t row_base = static_cast<size_t>(r) * ow * 3;
    for (int c = 0; c < ow; c++) {
      const int xa = xas[c], xb = xbs[c];
      const float wx = wxs[c];
      for (int ch = 0; ch < 3; ch++) {
        float top = (1.0f - wx) * rowa[xa + ch] + wx * rowa[xb + ch];
        float bot = (1.0f - wx) * rowb[xa + ch] + wx * rowb[xb + ch];
        st.put(row_base + c * 3 + ch, ch, (1.0f - wy) * top + wy * bot);
      }
    }
  }
}

// Dispatches the sampler on the wire format (out_u8 selects StoreU8).
static void bilinear_sample_out(const uint8_t* src, int sh, int sw,
                                void* dst, int out_u8, int oh, int ow,
                                int flip, float y_off, float y_step,
                                float x_off, float x_step,
                                const float* sub) {
  if (out_u8) {
    bilinear_sample_store(src, sh, sw, oh, ow, flip, y_off, y_step,
                          x_off, x_step,
                          StoreU8{static_cast<uint8_t*>(dst)});
  } else {
    bilinear_sample_store(src, sh, sw, oh, ow, flip, y_off, y_step,
                          x_off, x_step,
                          StoreF32Sub{static_cast<float*>(dst), sub});
  }
}

// One image: fused decode-crop-(flip)-resize-mean-subtract.  With
// scaled_decode, crops larger than the output decode at the smallest
// N/8 DCT-space scale (libjpeg-turbo scale_num=N) that keeps the
// scaled crop >= the output — engaged only for N <= 4 (crop >= 2x the
// output): measured on libjpeg-turbo, N=5..7 scaled decodes LOSE to
// the full decode (no SIMD for the odd reduced IDCT sizes, and entropy
// decode — the constant cost scaling can't skip — dominates small
// images), while N<=4 wins 10-30%.  Returns 0 on success.
static int decode_resize_one(const uint8_t* buf, int64_t len, int y, int x,
                             int ch, int cw, int flip, int oh, int ow,
                             const float* sub, void* dst, int out_u8,
                             int fast_dct, int scaled_decode,
                             std::vector<uint8_t>& tmp) {
  if (ch <= 0 || cw <= 0) return 1;
  int num = 8;
  if (scaled_decode) {
    const int n_h = (8 * oh + ch - 1) / ch;
    const int n_w = (8 * ow + cw - 1) / cw;
    const int nsel = n_h > n_w ? n_h : n_w;
    if (nsel >= 1 && nsel <= 4) num = nsel;
  }
  const float ys = static_cast<float>(ch) / oh;
  const float xs = static_cast<float>(cw) / ow;
  if (num == 8) {
    tmp.resize(static_cast<size_t>(ch) * cw * 3);
    if (jpeg_decode_crop_impl(buf, len, y, x, ch, cw, tmp.data(),
                              fast_dct))
      return 1;
    bilinear_sample_out(tmp.data(), ch, cw, dst, out_u8, oh, ow, flip,
                        0.5f * ys - 0.5f, ys, 0.5f * xs - 0.5f, xs, sub);
  } else {
    // decode window in N/8-scaled coordinates covering the crop
    const float s = num / 8.0f;
    const int y0s = y * num / 8, x0s = x * num / 8;
    const int chs = ((y + ch) * num + 7) / 8 - y0s;
    const int cws = ((x + cw) * num + 7) / 8 - x0s;
    tmp.resize(static_cast<size_t>(chs) * cws * 3);
    if (jpeg_decode_crop_impl(buf, len, y0s, x0s, chs, cws, tmp.data(),
                              fast_dct, num))
      return 1;
    // full-res source coord f sits at (f + 0.5)*s - 0.5 in scaled
    // space; carry the crop origin and window offset through
    bilinear_sample_out(tmp.data(), chs, cws, dst, out_u8, oh, ow, flip,
                        (y + 0.5f * ys) * s - 0.5f - y0s, ys * s,
                        (x + 0.5f * xs) * s - 0.5f - x0s, xs * s, sub);
  }
  return 0;
}

extern "C" {

// Capability marker: a library exporting this symbol supports the
// uint8 wire (trailing out_u8 parameter on the fused batch ops).  The
// Python layer gates uint8 mode on it so a stale .so degrades to the
// float32 wire instead of writing garbage.
int dtf_wire_u8(void) { return 1; }

// Per-image destination in the wire's element stride (px = oh*ow*3).
static inline void* dst_at(void* out, int out_u8, int i, size_t px) {
  return out_u8
      ? static_cast<void*>(static_cast<uint8_t*>(out) + i * px)
      : static_cast<void*>(static_cast<float*>(out) + i * px);
}

int dtf_jpeg_decode_crop_resize_batch(
    const uint8_t** bufs, const int64_t* lens, int n, const int* crops,
    const uint8_t* flips, int oh, int ow, const float* sub, void* out,
    uint8_t* statuses, int num_threads, int fast_dct, int scaled_decode,
    int out_u8) {
  const size_t px = static_cast<size_t>(oh) * ow * 3;
  std::atomic<int> next(0), failures(0);
  auto work = [&]() {
    std::vector<uint8_t> tmp;
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      const int* c = crops + i * 4;
      void* dst = dst_at(out, out_u8, i, px);
      if (decode_resize_one(bufs[i], lens[i], c[0], c[1], c[2], c[3],
                            flips ? flips[i] : 0, oh, ow, sub, dst,
                            out_u8, fast_dct, scaled_decode, tmp)) {
        statuses[i] = 1;
        failures.fetch_add(1);
        continue;
      }
      statuses[i] = 0;
    }
  };
  if (num_threads <= 1) {
    work();
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < num_threads; t++) threads.emplace_back(work);
    for (auto& t : threads) t.join();
  }
  return failures.load();
}

// ---------------------------------------------------------------------------
// tf.train.Example wire parse (targeted) + distorted-bbox sampling —
// the whole per-record train path in one call: parse → JPEG header →
// sample crop → flip → fused decode-crop-resize-subtract.  This is the
// GIL-held Python work the r3 instrumentation measured as the input
// pipeline's Amdahl serial fraction, moved off the interpreter.
//
// Wire format (records.py build_example / TF parity): Example{1:
// Features{1: map entry{1: key, 2: Feature}}}; Feature{1: BytesList,
// 2: FloatList (packed), 3: Int64List (packed varints)}.
// ---------------------------------------------------------------------------

// Reads a base-128 varint; returns false on truncation.
static bool read_varint(const uint8_t*& p, const uint8_t* end,
                        uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    v |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

// Skips a field payload by wiretype; returns false on malformed input.
static bool skip_field(const uint8_t*& p, const uint8_t* end, int wt) {
  uint64_t tmp;
  switch (wt) {
    case 0: return read_varint(p, end, &tmp);
    case 1: if (end - p < 8) return false; p += 8; return true;
    case 2:
      if (!read_varint(p, end, &tmp) ||
          static_cast<uint64_t>(end - p) < tmp)
        return false;
      p += tmp;
      return true;
    case 5: if (end - p < 4) return false; p += 4; return true;
    default: return false;
  }
}

struct ParsedExample {
  const uint8_t* encoded = nullptr;  // points into the record buffer
  uint64_t encoded_len = 0;
  int64_t label = -1;
  float bbox[4] = {0.f, 0.f, 1.f, 1.f};  // ymin, xmin, ymax, xmax
  bool has_bbox = false;
};

// Extracts the first value of the named features.  Returns false on a
// wire-format error or when image/encoded / label are absent.
static bool parse_train_example(const uint8_t* rec, int64_t len,
                                ParsedExample* out) {
  const uint8_t* p = rec;
  const uint8_t* end = rec + len;
  bool bbox_seen[4] = {false, false, false, false};
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return false;
    if ((tag >> 3) != 1 || (tag & 7) != 2) {  // Example.features
      if (!skip_field(p, end, tag & 7)) return false;
      continue;
    }
    uint64_t flen;
    if (!read_varint(p, end, &flen) ||
        static_cast<uint64_t>(end - p) < flen)
      return false;
    const uint8_t* fp = p;
    const uint8_t* fend = p + flen;
    p = fend;
    while (fp < fend) {  // Features.feature map entries
      uint64_t etag;
      if (!read_varint(fp, fend, &etag)) return false;
      if ((etag >> 3) != 1 || (etag & 7) != 2) {
        if (!skip_field(fp, fend, etag & 7)) return false;
        continue;
      }
      uint64_t elen;
      if (!read_varint(fp, fend, &elen) ||
          static_cast<uint64_t>(fend - fp) < elen)
        return false;
      const uint8_t* ep = fp;
      const uint8_t* eend = fp + elen;
      fp = eend;
      const uint8_t* key = nullptr;
      uint64_t key_len = 0;
      const uint8_t* feat = nullptr;
      uint64_t feat_len = 0;
      while (ep < eend) {  // map entry: key=1, Feature=2
        uint64_t ktag;
        if (!read_varint(ep, eend, &ktag)) return false;
        if ((ktag & 7) != 2) {
          if (!skip_field(ep, eend, ktag & 7)) return false;
          continue;
        }
        uint64_t klen;
        if (!read_varint(ep, eend, &klen) ||
            static_cast<uint64_t>(eend - ep) < klen)
          return false;
        if ((ktag >> 3) == 1) {
          key = ep;
          key_len = klen;
        } else if ((ktag >> 3) == 2) {
          feat = ep;
          feat_len = klen;
        }
        ep += klen;
      }
      if (!key || !feat) continue;
      std::string_view name(reinterpret_cast<const char*>(key), key_len);
      int bbox_idx = -1;
      if (name == "image/object/bbox/ymin") bbox_idx = 0;
      else if (name == "image/object/bbox/xmin") bbox_idx = 1;
      else if (name == "image/object/bbox/ymax") bbox_idx = 2;
      else if (name == "image/object/bbox/xmax") bbox_idx = 3;
      if (name != "image/encoded" && name != "image/class/label" &&
          bbox_idx < 0)
        continue;
      // Feature: one of BytesList/FloatList/Int64List at field 1..3
      const uint8_t* vp = feat;
      const uint8_t* vend = feat + feat_len;
      while (vp < vend) {
        uint64_t vtag;
        if (!read_varint(vp, vend, &vtag)) return false;
        if ((vtag & 7) != 2) {
          if (!skip_field(vp, vend, vtag & 7)) return false;
          continue;
        }
        uint64_t vlen;
        if (!read_varint(vp, vend, &vlen) ||
            static_cast<uint64_t>(vend - vp) < vlen)
          return false;
        const uint8_t* lp = vp;
        const uint8_t* lend = vp + vlen;
        vp = lend;
        // the list message: field 1 holds the value(s)
        while (lp < lend) {
          uint64_t ltag;
          if (!read_varint(lp, lend, &ltag)) return false;
          if ((ltag >> 3) != 1) {
            if (!skip_field(lp, lend, ltag & 7)) return false;
            continue;
          }
          if ((vtag >> 3) == 1 && (ltag & 7) == 2) {  // bytes value
            uint64_t blen;
            if (!read_varint(lp, lend, &blen) ||
                static_cast<uint64_t>(lend - lp) < blen)
              return false;
            if (name == "image/encoded" && !out->encoded) {
              out->encoded = lp;
              out->encoded_len = blen;
            }
            lp += blen;
          } else if ((vtag >> 3) == 2) {  // float list
            if ((ltag & 7) == 2) {  // packed
              uint64_t plen;
              if (!read_varint(lp, lend, &plen) ||
                  static_cast<uint64_t>(lend - lp) < plen || plen < 4)
                return false;
              if (bbox_idx >= 0 && !bbox_seen[bbox_idx]) {
                memcpy(&out->bbox[bbox_idx], lp, 4);  // first value
                bbox_seen[bbox_idx] = true;
              }
              lp += plen;
            } else if ((ltag & 7) == 5) {  // unpacked
              if (lend - lp < 4) return false;
              if (bbox_idx >= 0 && !bbox_seen[bbox_idx]) {
                memcpy(&out->bbox[bbox_idx], lp, 4);
                bbox_seen[bbox_idx] = true;
              }
              lp += 4;
            } else {
              if (!skip_field(lp, lend, ltag & 7)) return false;
            }
          } else if ((vtag >> 3) == 3) {  // int64 list
            if ((ltag & 7) == 2) {  // packed varints
              uint64_t plen;
              if (!read_varint(lp, lend, &plen) ||
                  static_cast<uint64_t>(lend - lp) < plen)
                return false;
              const uint8_t* ip = lp;
              uint64_t v;
              if (name == "image/class/label" && out->label < 0 &&
                  read_varint(ip, lp + plen, &v))
                out->label = static_cast<int64_t>(v);
              lp += plen;
            } else if ((ltag & 7) == 0) {  // single varint
              uint64_t v;
              if (!read_varint(lp, lend, &v)) return false;
              if (name == "image/class/label" && out->label < 0)
                out->label = static_cast<int64_t>(v);
            } else {
              if (!skip_field(lp, lend, ltag & 7)) return false;
            }
          } else {
            if (!skip_field(lp, lend, ltag & 7)) return false;
          }
        }
      }
    }
  }
  out->has_bbox = bbox_seen[0] && bbox_seen[1] && bbox_seen[2] &&
                  bbox_seen[3];
  return out->encoded != nullptr && out->label >= 0;
}

// splitmix64: per-image deterministic stream independent of thread
// scheduling (seed ^ f(index) — stronger reproducibility than a
// shared sequential generator).
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {}
  uint64_t next() {
    s += 0x9E3779B97F4A7C15ull;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }
  double uniform() {  // [0, 1)
    return (next() >> 11) * 0x1.0p-53;
  }
  double uniform(double lo, double hi) { return lo + (hi - lo) * uniform(); }
  int64_t below(int64_t n) {  // [0, n)
    return static_cast<int64_t>(uniform() * n);
  }
};

// Mirror of data/imagenet.py sample_distorted_bbox (reference
// imagenet_preprocessing.py:345-361 constants): min_object_covered
// 0.1, aspect in [0.75, 1.33], area in [0.05, 1.0], 100 attempts,
// whole image on failure.
static void sample_distorted_bbox(Rng& rng, int height, int width,
                                  const float* bbox, bool has_bbox,
                                  int* out) {
  const float by0 = (has_bbox ? bbox[0] : 0.f) * height;
  const float bx0 = (has_bbox ? bbox[1] : 0.f) * width;
  const float by1 = (has_bbox ? bbox[2] : 1.f) * height;
  const float bx1 = (has_bbox ? bbox[3] : 1.f) * width;
  const float box_area =
      std::max((by1 - by0) * (bx1 - bx0), 1e-6f);
  for (int attempt = 0; attempt < 100; ++attempt) {
    const double aspect = rng.uniform(0.75, 1.33);
    const double area_frac = rng.uniform(0.05, 1.0);
    const double target_area =
        area_frac * static_cast<double>(height) * width;
    const int w = static_cast<int>(std::lround(std::sqrt(target_area * aspect)));
    const int h = static_cast<int>(std::lround(std::sqrt(target_area / aspect)));
    if (w > width || h > height || h <= 0 || w <= 0) continue;
    const int y = static_cast<int>(rng.below(height - h + 1));
    const int x = static_cast<int>(rng.below(width - w + 1));
    const float inter_h =
        std::max(0.f, std::min<float>(y + h, by1) - std::max<float>(y, by0));
    const float inter_w =
        std::max(0.f, std::min<float>(x + w, bx1) - std::max<float>(x, bx0));
    if (inter_h * inter_w / box_area >= 0.1f) {
      out[0] = y; out[1] = x; out[2] = h; out[3] = w;
      return;
    }
  }
  out[0] = 0; out[1] = 0; out[2] = height; out[3] = width;
}

// The whole train path for a batch of raw Example records.  statuses:
// 0 ok, 1 parse failed (caller reprocesses in Python), 2 decode failed
// (caller re-decodes with the RETURNED crop/flip so augmentation stays
// identical).  labels/crops/flips are always filled for status != 1.
// Returns the failure count.
int dtf_train_example_batch(
    const uint8_t** recs, const int64_t* lens, int n, uint64_t seed,
    int oh, int ow, const float* sub, int fast_dct, int scaled_decode,
    int num_threads, void* out, int32_t* labels, int32_t* crops,
    uint8_t* flips, uint8_t* statuses, int out_u8) {
  const size_t px = static_cast<size_t>(oh) * ow * 3;
  std::atomic<int> next(0), failures(0);
  auto work = [&]() {
    std::vector<uint8_t> tmp;
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      ParsedExample ex;
      if (!parse_train_example(recs[i], lens[i], &ex)) {
        statuses[i] = 1;
        failures.fetch_add(1);
        continue;
      }
      labels[i] = static_cast<int32_t>(ex.label - 1);  // → [0, 1000)
      int h = 0, w = 0;
      Rng rng(seed ^ (0xA0761D6478BD642Full * (i + 1)));
      int* crop = crops + i * 4;
      if (dtf_jpeg_shape(ex.encoded, ex.encoded_len, &h, &w) ||
          h <= 0 || w <= 0) {
        statuses[i] = 1;  // undecodable header → Python whole path
        failures.fetch_add(1);
        continue;
      }
      sample_distorted_bbox(rng, h, w, ex.bbox, ex.has_bbox, crop);
      const int flip = rng.uniform() < 0.5 ? 1 : 0;
      flips[i] = static_cast<uint8_t>(flip);
      void* dst = dst_at(out, out_u8, i, px);
      if (decode_resize_one(ex.encoded, ex.encoded_len, crop[0], crop[1],
                            crop[2], crop[3], flip, oh, ow, sub, dst,
                            out_u8, fast_dct, scaled_decode, tmp)) {
        statuses[i] = 2;
        failures.fetch_add(1);
        continue;
      }
      statuses[i] = 0;
    }
  };
  if (num_threads <= 1) {
    work();
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < num_threads; t++) threads.emplace_back(work);
    for (auto& t : threads) t.join();
  }
  return failures.load();
}

// ---------------------------------------------------------------------------
// Fused eval-side batch: aspect-preserving resize to shorter-side
// `resize_min` + central [oh, ow] crop + mean-subtract, in ONE sampling
// pass over a decode WINDOW (only the source rows/cols the crop
// samples are decoded — imagenet_preprocessing.py:375-394,464-480
// semantics with tf-bilinear numerics).
// ---------------------------------------------------------------------------

int dtf_jpeg_eval_batch(const uint8_t** bufs, const int64_t* lens, int n,
                        int resize_min, int oh, int ow, const float* sub,
                        void* out, uint8_t* statuses, int num_threads,
                        int fast_dct, int out_u8) {
  const size_t px = static_cast<size_t>(oh) * ow * 3;
  std::atomic<int> next(0), failures(0);
  auto work = [&]() {
    std::vector<uint8_t> tmp;
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      int h = 0, w = 0;
      if (dtf_jpeg_shape(bufs[i], lens[i], &h, &w) || h <= 0 || w <= 0) {
        statuses[i] = 1;
        failures.fetch_add(1);
        continue;
      }
      const float scale =
          static_cast<float>(resize_min) / (h < w ? h : w);
      const int nh = static_cast<int>(lroundf(h * scale));
      const int nw = static_cast<int>(lroundf(w * scale));
      if (nh < oh || nw < ow) {  // resize_min must cover the crop
        statuses[i] = 1;
        failures.fetch_add(1);
        continue;
      }
      const float ys = static_cast<float>(h) / nh;
      const float xs = static_cast<float>(w) / nw;
      const float y_off = ((nh - oh) / 2 + 0.5f) * ys - 0.5f;
      const float x_off = ((nw - ow) / 2 + 0.5f) * xs - 0.5f;
      // source window actually sampled (clamp handles the edges)
      int y0 = static_cast<int>(floorf(y_off));
      int y1 = static_cast<int>(floorf(y_off + (oh - 1) * ys)) + 1;
      int x0 = static_cast<int>(floorf(x_off));
      int x1 = static_cast<int>(floorf(x_off + (ow - 1) * xs)) + 1;
      y0 = y0 < 0 ? 0 : y0;
      x0 = x0 < 0 ? 0 : x0;
      y1 = y1 >= h ? h - 1 : y1;
      x1 = x1 >= w ? w - 1 : x1;
      const int wh = y1 - y0 + 1, ww = x1 - x0 + 1;
      tmp.resize(static_cast<size_t>(wh) * ww * 3);
      if (jpeg_decode_crop_impl(bufs[i], lens[i], y0, x0, wh, ww,
                                tmp.data(), fast_dct)) {
        statuses[i] = 1;
        failures.fetch_add(1);
        continue;
      }
      void* dst = dst_at(out, out_u8, i, px);
      bilinear_sample_out(tmp.data(), wh, ww, dst, out_u8,
                          oh, ow, /*flip=*/0, y_off - y0, ys,
                          x_off - x0, xs, sub);
      statuses[i] = 0;
    }
  };
  if (num_threads <= 1) {
    work();
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < num_threads; t++) threads.emplace_back(work);
    for (auto& t : threads) t.join();
  }
  return failures.load();
}

}  // extern "C"
