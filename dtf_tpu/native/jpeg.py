"""JPEG decode via the native library (libjpeg-turbo).

`decode` and `decode_crop` mirror tf.image.decode_jpeg /
decode_and_crop_jpeg (the fused op the reference leans on,
imagenet_preprocessing.py:363-368).  ctypes calls release the GIL, so
calling these from Python worker threads scales across cores.
"""

from __future__ import annotations

import ctypes

import numpy as np

from dtf_tpu.native import load


def _lib():
    lib = load()
    if lib is None:
        raise ImportError("libdtf_native.so not built; run "
                          "`make -C dtf_tpu/native`")
    return lib


def shape(buf: bytes):
    """(height, width) from the JPEG header only."""
    lib = _lib()
    h = ctypes.c_int()
    w = ctypes.c_int()
    arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    if lib.dtf_jpeg_shape(arr, len(buf), ctypes.byref(h), ctypes.byref(w)):
        raise ValueError("invalid JPEG")
    return h.value, w.value


def decode_crop(buf: bytes, y: int, x: int, ch: int, cw: int) -> np.ndarray:
    """Fused decode-and-crop → RGB uint8 [ch, cw, 3]."""
    lib = _lib()
    out = np.empty((ch, cw, 3), np.uint8)
    arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    rc = lib.dtf_jpeg_decode_crop(
        arr, len(buf), y, x, ch, cw,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc:
        raise ValueError(f"JPEG decode failed (rc={rc})")
    return out


def decode(buf: bytes) -> np.ndarray:
    """Full-image RGB uint8 decode."""
    h, w = shape(buf)
    return decode_crop(buf, 0, 0, h, w)
