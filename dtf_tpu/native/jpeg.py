"""JPEG decode via the native library (libjpeg-turbo).

`decode` and `decode_crop` mirror tf.image.decode_jpeg /
decode_and_crop_jpeg (the fused op the reference leans on,
imagenet_preprocessing.py:363-368).  ctypes calls release the GIL, so
calling these from Python worker threads scales across cores.
"""

from __future__ import annotations

import ctypes

import numpy as np

from dtf_tpu.native import load


def _lib():
    lib = load()
    if lib is None:
        raise ImportError("libdtf_native.so not built; run "
                          "`make -C dtf_tpu/native`")
    return lib


def shape(buf: bytes):
    """(height, width) from the JPEG header only."""
    lib = _lib()
    h = ctypes.c_int()
    w = ctypes.c_int()
    if lib.dtf_jpeg_shape(buf, len(buf), ctypes.byref(h), ctypes.byref(w)):
        raise ValueError("invalid JPEG")
    return h.value, w.value


def decode_crop(buf: bytes, y: int, x: int, ch: int, cw: int) -> np.ndarray:
    """Fused decode-and-crop → RGB uint8 [ch, cw, 3]."""
    lib = _lib()
    out = np.empty((ch, cw, 3), np.uint8)
    rc = lib.dtf_jpeg_decode_crop(
        buf, len(buf), y, x, ch, cw,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc:
        raise ValueError(f"JPEG decode failed (rc={rc})")
    return out


def decode(buf: bytes) -> np.ndarray:
    """Full-image RGB uint8 decode."""
    h, w = shape(buf)
    return decode_crop(buf, 0, 0, h, w)


def decode_batch(bufs, crops, ch: int, cw: int,
                 num_threads: int = 4) -> np.ndarray:
    """Decode-and-crop ``len(bufs)`` JPEGs in parallel C++ threads.

    ``crops``: sequence of (y, x, h, w) per image, with h == ch and
    w == cw (one fixed output geometry per batch — the training path's
    shape anyway).  Returns uint8 [n, ch, cw, 3]; raises on any failed
    image.
    """
    lib = _lib()
    n = len(bufs)
    out = np.empty((n, ch, cw, 3), np.uint8)
    buf_ptrs = (ctypes.c_char_p * n)(*bufs)
    lens = (ctypes.c_int64 * n)(*[len(b) for b in bufs])
    crop_arr = (ctypes.c_int * (4 * n))(
        *[int(v) for c in crops for v in c])
    failures = lib.dtf_jpeg_decode_batch(
        buf_ptrs, lens, n, crop_arr, ch, cw,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), num_threads)
    if failures:
        raise ValueError(f"{failures}/{n} JPEGs failed to decode")
    return out


def _out_ptr(lib, out):
    """Output pointer matching the declared argtype: void* on u8-wire
    libraries, float* on older builds."""
    if hasattr(lib, "dtf_wire_u8"):
        return out.ctypes.data_as(ctypes.c_void_p)
    return out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u8_tail(lib, out_u8: bool):
    """Trailing out_u8 argument — only on libraries whose signature has
    it (callers already raised if out_u8 was requested without it)."""
    return (int(out_u8),) if hasattr(lib, "dtf_wire_u8") else ()


def wire_u8_supported() -> bool:
    """True when the built library supports the uint8 output wire
    (the trailing ``out_u8`` parameter on the fused batch ops).  A
    stale .so without the marker symbol degrades to the float32 wire."""
    lib = load()
    return lib is not None and hasattr(lib, "dtf_wire_u8")


def decode_crop_resize_batch(bufs, crops, flips, out_h: int, out_w: int,
                             sub, num_threads: int = 4,
                             fast_dct: bool = False,
                             scaled_decode: bool = False,
                             out_u8: bool = False):
    """The whole train-time augmentation for a batch in one C++ call:
    fused decode-and-crop (per-image variable windows) → horizontal
    flip → bilinear resize (half-pixel centers, tf.image.resize v2
    semantics) → channel-mean subtraction, across ``num_threads``
    GIL-free threads.

    ``fast_dct`` selects libjpeg's JDCT_IFAST (±1-2 LSB vs the default
    ISLOW, measurably faster IDCT) — augmentation-noise territory for
    training, so it is a throughput opt-in, never a default.

    ``scaled_decode``: crops >=2x the output are decoded at the
    smallest N/8 resolution (libjpeg-turbo DCT-space scaling, N<=4)
    that keeps the scaled crop >= the output — a 460px crop bound for
    224 decodes at half resolution.  Measured win is 10-30% on such
    crops (entropy decode, which scaling cannot skip, bounds it);
    N=5..7 scales measured slower than the full decode (no SIMD for
    the odd reduced IDCT sizes) and are never used.  Changes the
    downsampling filter chain, not the crop geometry; a throughput
    opt-in for large-image datasets, never a default.

    ``out_u8``: uint8 output wire — pixels round-half-up post-resize,
    NO mean subtraction (normalization moves into the compiled step on
    the accelerator; 4x fewer host→device bytes).  Requires a library
    with :func:`wire_u8_supported`.

    Returns (float32|uint8 [n, out_h, out_w, 3], ok mask bool [n]);
    failed images (rare decoder edge cases) have ok=False and undefined
    content — the caller re-decodes them however it likes.
    """
    lib = _lib()
    if out_u8 and not hasattr(lib, "dtf_wire_u8"):
        raise ImportError("libdtf_native.so predates the uint8 wire; "
                          "rebuild (make -C dtf_tpu/native)")
    n = len(bufs)
    out = np.empty((n, out_h, out_w, 3),
                   np.uint8 if out_u8 else np.float32)
    statuses = np.empty((n,), np.uint8)
    buf_ptrs = (ctypes.c_char_p * n)(*bufs)
    lens = (ctypes.c_int64 * n)(*[len(b) for b in bufs])
    crop_arr = (ctypes.c_int * (4 * n))(
        *[int(v) for c in crops for v in c])
    flip_arr = np.ascontiguousarray(np.asarray(flips, np.uint8))
    sub_arr = np.ascontiguousarray(np.asarray(sub, np.float32))
    lib.dtf_jpeg_decode_crop_resize_batch(
        buf_ptrs, lens, n, crop_arr,
        flip_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out_h, out_w,
        sub_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _out_ptr(lib, out),
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        num_threads, int(fast_dct), int(scaled_decode), *_u8_tail(lib, out_u8))
    return out, statuses == 0


def train_example_batch(records, seed: int, out_h: int, out_w: int, sub,
                        num_threads: int = 4, fast_dct: bool = False,
                        scaled_decode: bool = False,
                        out_u8: bool = False):
    """The whole train path for a batch of raw tf.train.Example
    records in one C++ call: proto parse (image/encoded, label, first
    bbox) → JPEG header → distorted-bbox sampling (reference
    constants; splitmix64 per-image streams seeded by ``seed``) →
    flip → fused decode-crop-resize-mean-subtract.  This is the
    formerly GIL-held per-record Python work (the input pipeline's
    measured Amdahl serial fraction), off the interpreter.

    ``out_u8``: uint8 output wire (see
    :func:`decode_crop_resize_batch`).

    Returns (images f32|u8 [n,oh,ow,3], labels i32 [n] (shifted to
    [0,1000)), crops i32 [n,4], flips u8 [n], statuses u8 [n]):
    status 0 ok; 1 parse/header failure (reprocess the record in
    Python); 2 decode failure (re-decode with the returned crop/flip
    so the augmentation stays identical).
    """
    lib = _lib()
    if not hasattr(lib, "dtf_train_example_batch"):
        raise ImportError("libdtf_native.so predates "
                          "dtf_train_example_batch; rebuild")
    if out_u8 and not hasattr(lib, "dtf_wire_u8"):
        raise ImportError("libdtf_native.so predates the uint8 wire; "
                          "rebuild (make -C dtf_tpu/native)")
    n = len(records)
    out = np.empty((n, out_h, out_w, 3),
                   np.uint8 if out_u8 else np.float32)
    labels = np.empty((n,), np.int32)
    crops = np.empty((n, 4), np.int32)
    flips = np.empty((n,), np.uint8)
    statuses = np.empty((n,), np.uint8)
    rec_ptrs = (ctypes.c_char_p * n)(*records)
    lens = (ctypes.c_int64 * n)(*[len(r) for r in records])
    sub_arr = np.ascontiguousarray(np.asarray(sub, np.float32))
    lib.dtf_train_example_batch(
        rec_ptrs, lens, n, ctypes.c_uint64(seed & (2**64 - 1)),
        out_h, out_w,
        sub_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(fast_dct), int(scaled_decode), num_threads,
        _out_ptr(lib, out),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        crops.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        flips.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        *_u8_tail(lib, out_u8))
    return out, labels, crops, flips, statuses


def eval_batch(bufs, resize_min: int, out_h: int, out_w: int, sub,
               num_threads: int = 4, fast_dct: bool = False,
               out_u8: bool = False):
    """Fused eval preprocessing for a batch: aspect-preserving resize to
    shorter-side ``resize_min`` + central [out_h, out_w] crop +
    channel-mean subtraction in one sampling pass over a decode window
    (only the needed source rows/cols are decoded).  tf-bilinear
    numerics — the reference's eval path
    (imagenet_preprocessing.py:375-394,464-480).

    ``out_u8``: uint8 output wire (see
    :func:`decode_crop_resize_batch`).

    Returns (float32|uint8 [n, out_h, out_w, 3], ok mask bool [n]).
    """
    lib = _lib()
    if out_u8 and not hasattr(lib, "dtf_wire_u8"):
        raise ImportError("libdtf_native.so predates the uint8 wire; "
                          "rebuild (make -C dtf_tpu/native)")
    n = len(bufs)
    out = np.empty((n, out_h, out_w, 3),
                   np.uint8 if out_u8 else np.float32)
    statuses = np.empty((n,), np.uint8)
    buf_ptrs = (ctypes.c_char_p * n)(*bufs)
    lens = (ctypes.c_int64 * n)(*[len(b) for b in bufs])
    sub_arr = np.ascontiguousarray(np.asarray(sub, np.float32))
    lib.dtf_jpeg_eval_batch(
        buf_ptrs, lens, n, resize_min, out_h, out_w,
        sub_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _out_ptr(lib, out),
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        num_threads, int(fast_dct), *_u8_tail(lib, out_u8))
    return out, statuses == 0
